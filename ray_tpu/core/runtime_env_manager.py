"""Runtime-env manager: pluggable per-env worker environments.

Equivalent of the reference's runtime-env agent + plugin architecture
(`dashboard/modules/runtime_env/runtime_env_agent.py:161`,
`_private/runtime_env/{plugin,pip,conda}.py`): every runtime-env FIELD that
needs machinery is a PLUGIN — a named unit with a spec normalizer, a
create step (run once per content-addressed key under a cross-process
lock), a context hook (which interpreter / env vars workers get), and a
delete step driven by URI-style reference counts. `pip` (virtualenv) and
`conda` ship built in; third parties register theirs with
`register_plugin` without touching the manager.

Lightweight fields (env_vars, working_dir) are applied in-process by the
worker (`core/worker.py _apply_runtime_env`) and need no plugin.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_DEFAULT_BASE = "/tmp/ray_tpu/runtime_envs"


@dataclass
class EnvContext:
    """What a plugin contributes to worker startup.

    command_prefix wraps the worker's argv (container engines, launchers):
    the raylet runs `command_prefix + [python, -m, worker_main, ...]`.
    A literal "{ENVFILE}" element is replaced at spawn time with the path
    of a KEY=VALUE file holding the worker's environment (how env vars
    cross a container boundary)."""

    python: str = sys.executable
    env_vars: Dict[str, str] = field(default_factory=dict)
    command_prefix: List[str] = field(default_factory=list)


class RuntimeEnvPlugin:
    """One runtime-env field's machinery (reference RuntimeEnvPlugin,
    _private/runtime_env/plugin.py). Subclass and `register_plugin()`.

    name:   the runtime_env dict key this plugin owns (e.g. "pip")
    pooled: True if workers must be pooled per env key (an interpreter or
            sys.path change); False for fields any worker can apply
    """

    name: str = ""
    pooled: bool = True

    def key_spec(self, value: Any) -> Any:
        """Normalized, hashable spec for content addressing."""
        return value

    def create(self, value: Any, env_dir: str) -> None:
        """Build the environment under env_dir (called once per key,
        cross-process locked). Raise on failure."""

    def modify_context(self, value: Any, env_dir: str,
                       ctx: EnvContext) -> None:
        """Point the worker context at the built environment."""

    def delete(self, env_dir: str) -> None:
        """Reclaim the built environment (refcount hit zero)."""
        shutil.rmtree(env_dir, ignore_errors=True)


# ------------------------------------------------------------ registration

_plugins: Dict[str, RuntimeEnvPlugin] = {}
_plugins_lock = threading.Lock()


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a name (the runtime_env key it owns)")
    with _plugins_lock:
        _plugins[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    with _plugins_lock:
        _plugins.pop(name, None)


def _active_plugins(runtime_env: dict) -> List[RuntimeEnvPlugin]:
    with _plugins_lock:
        plugins = list(_plugins.values())
    return [p for p in plugins if runtime_env.get(p.name)]


# ---------------------------------------------------------------- builtins


class PipPlugin(RuntimeEnvPlugin):
    """Virtualenv-backed pip env (--system-site-packages so jax/numpy
    resolve from the base image, like the reference's pip plugin)."""

    name = "pip"

    def key_spec(self, value):
        return sorted(self._packages(value))

    def _packages(self, value) -> List[str]:
        if isinstance(value, dict):  # {"packages": [...]} form
            value = value.get("packages", [])
        return [str(p) for p in value or []]

    def create(self, value, env_dir: str) -> None:
        import sysconfig

        pip = self._packages(value)
        py = os.path.join(env_dir, "bin", "python")
        logger.info("creating pip runtime env at %s (pip=%s)", env_dir, pip)
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", env_dir],
            check=True, capture_output=True)
        # When this process itself runs in a venv, --system-site-packages
        # points at the *base* interpreter, not our parent venv — link the
        # parent's site-packages too (after the env's own dir, so installed
        # packages shadow inherited ones).
        child_purelib = subprocess.run(
            [py, "-c",
             "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
            check=True, capture_output=True, text=True).stdout.strip()
        parent_purelib = sysconfig.get_paths()["purelib"]
        if parent_purelib != child_purelib:
            with open(os.path.join(child_purelib, "_parent_site.pth"),
                      "w") as f:
                f.write(parent_purelib + "\n")
        if pip:
            r = subprocess.run(
                [py, "-m", "pip", "install", "--no-input", *pip],
                capture_output=True, text=True, timeout=600)
            if r.returncode != 0:
                raise RuntimeError(r.stderr[-2000:])

    def modify_context(self, value, env_dir: str, ctx: EnvContext) -> None:
        ctx.python = os.path.join(env_dir, "bin", "python")


class CondaPlugin(RuntimeEnvPlugin):
    """Conda env support (reference _private/runtime_env/conda.py):
    `{"conda": {"dependencies": [...]}}` builds a prefix env;
    `{"conda": "existing-env-name"}` reuses a named env. Requires a conda
    binary on PATH."""

    name = "conda"

    def key_spec(self, value):
        if isinstance(value, str):
            return value
        return json.dumps(value, sort_keys=True)

    @staticmethod
    def _conda() -> str:
        exe = shutil.which("conda") or shutil.which("mamba")
        if exe is None:
            raise RuntimeError(
                "runtime_env 'conda' requires a conda/mamba binary on PATH")
        return exe

    def create(self, value, env_dir: str) -> None:
        import tempfile

        conda = self._conda()
        if isinstance(value, str):
            return  # named env: nothing to build
        deps = list((value or {}).get("dependencies", []))
        spec = {"dependencies": deps or [f"python={sys.version_info.major}."
                                         f"{sys.version_info.minor}"]}
        os.makedirs(os.path.dirname(env_dir), exist_ok=True)
        fd, spec_path = tempfile.mkstemp(suffix=".yaml")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump({"name": "rtpu", **spec}, f)  # yaml-subset JSON
            r = subprocess.run(
                [conda, "env", "create", "-p", env_dir, "-f", spec_path],
                capture_output=True, text=True, timeout=1800)
            if r.returncode != 0:
                raise RuntimeError(r.stderr[-2000:])
        finally:
            os.unlink(spec_path)

    def modify_context(self, value, env_dir: str, ctx: EnvContext) -> None:
        if isinstance(value, str):
            conda_root = os.path.dirname(os.path.dirname(self._conda()))
            py = os.path.join(conda_root, "envs", value, "bin", "python")
            if not os.path.exists(py):
                # validate NOW: a bad named env must fail the queued tasks,
                # not FileNotFoundError the spawn thread later
                raise RuntimeError(
                    f"conda env {value!r} not found (no {py})")
            ctx.python = py
        else:
            ctx.python = os.path.join(env_dir, "bin", "python")


class _PyModulesPlugin(RuntimeEnvPlugin):
    """py_modules mutate sys.path for the worker's lifetime, so workers are
    pooled per package set; the download/sys.path work happens in-worker
    (runtime_env.ensure_py_modules)."""

    name = "py_modules"

    def key_spec(self, value):
        return sorted(str(m.get("uri", m) if isinstance(m, dict) else m)
                      for m in value or [])


def build_container_command(spec: dict, *, engine: str,
                            pkg_root: Optional[str] = None,
                            base_dir: str = _DEFAULT_BASE) -> List[str]:
    """Assemble the `docker|podman run` prefix that wraps a worker
    (reference python/ray/_private/runtime_env/container.py
    `get_container_option` → worker command wrapping). Pure function so
    request shape is unit-testable without a container daemon.

    The container shares the host network (raylet/GCS run on host TCP
    ports), the shared-memory arena (/dev/shm bind mount), the runtime-env
    base dir (session artifacts), and a read-only mount of the framework
    source; the worker env crosses the boundary via --env-file (the
    "{ENVFILE}" placeholder is materialized at spawn)."""
    image = spec.get("image")
    if not image:
        raise ValueError("container runtime_env needs an 'image'")
    if pkg_root is None:
        import ray_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    cmd = [engine, "run", "--rm", "--network=host",
           "-v", "/dev/shm:/dev/shm",
           "-v", f"{base_dir}:{base_dir}",
           "-v", f"{pkg_root}:{pkg_root}:ro",
           "--env-file", "{ENVFILE}"]
    cmd += [str(o) for o in spec.get("run_options", [])]
    cmd.append(image)
    return cmd


class ContainerPlugin(RuntimeEnvPlugin):
    """`{"container": {"image": ..., "run_options": [...], "engine": ...,
    "python": ...}}` runs the worker inside a container (reference
    `python/ray/_private/runtime_env/container.py`). Requires docker or
    podman on PATH at create time; `python` names the interpreter INSIDE
    the image (default python3)."""

    name = "container"

    @staticmethod
    def _norm(value) -> dict:
        if isinstance(value, str):
            return {"image": value}
        return dict(value or {})

    def key_spec(self, value):
        return json.dumps(self._norm(value), sort_keys=True)

    @staticmethod
    def _engine(spec: dict) -> str:
        eng = spec.get("engine")
        if eng:
            if shutil.which(eng) is None:
                raise RuntimeError(
                    f"container engine {eng!r} not found on PATH")
            return eng
        for cand in ("podman", "docker"):
            if shutil.which(cand):
                return cand
        raise RuntimeError(
            "runtime_env 'container' requires docker or podman on PATH")

    def create(self, value, env_dir: str) -> None:
        spec = self._norm(value)
        if not spec.get("image"):
            raise RuntimeError("container runtime_env needs an 'image'")
        self._engine(spec)  # fail fast where no container runtime exists
        os.makedirs(env_dir, exist_ok=True)

    def modify_context(self, value, env_dir: str, ctx: EnvContext) -> None:
        spec = self._norm(value)
        ctx.command_prefix = build_container_command(
            spec, engine=self._engine(spec))
        # the interpreter path must resolve INSIDE the image
        ctx.python = spec.get("python", "python3")


register_plugin(PipPlugin())
register_plugin(CondaPlugin())
register_plugin(_PyModulesPlugin())
register_plugin(ContainerPlugin())


# ------------------------------------------------------------------- keys


def env_key(runtime_env: Optional[dict]) -> Optional[str]:
    """Stable key for envs that need a dedicated worker pool; None when any
    worker can run the task after in-process env application."""
    if not runtime_env:
        return None
    active = [p for p in _active_plugins(runtime_env) if p.pooled]
    if not active:
        return None
    spec = {p.name: p.key_spec(runtime_env[p.name]) for p in active}
    return hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


class RuntimeEnvManager:
    """Creates, caches, refcounts and deletes plugin-built environments;
    thread-safe, one creation per key (cross-process file lock)."""

    def __init__(self, base_dir: str = _DEFAULT_BASE):
        self.base_dir = base_dir
        self._lock = threading.Lock()
        self._locks: Dict[str, threading.Lock] = {}
        self._failed: Dict[str, str] = {}
        self._refs: Dict[str, int] = {}  # URI-style env refcounts
        self._zero_since: Dict[str, float] = {}  # key -> t at refcount 0

    def _key_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._locks.setdefault(key, threading.Lock())

    def creation_error(self, key: str) -> Optional[str]:
        return self._failed.get(key)

    # ---------------------------------------------------------- refcounts
    # Counts are kept BOTH in-process (fast) and in an on-disk counter file
    # mutated under the key's cross-process flock: the base dir is shared
    # across raylets on a host, and a gc() in one process must never delete
    # an env another raylet's live workers run from.

    def _refs_path(self, key: str) -> str:
        return os.path.join(self.base_dir, f".{key}.refs")

    def _bump_disk_refs(self, key: str, delta: int) -> int:
        import fcntl

        os.makedirs(self.base_dir, exist_ok=True)
        with open(os.path.join(self.base_dir, f".{key}.lock"), "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                try:
                    with open(self._refs_path(key)) as f:
                        n = int(f.read().strip() or 0)
                except (FileNotFoundError, ValueError):
                    n = 0
                n = max(0, n + delta)
                with open(self._refs_path(key), "w") as f:
                    f.write(str(n))
                return n
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)

    def acquire(self, key: str) -> None:
        """One more worker serves this env (reference URI refcounting,
        runtime_env_agent URI cache)."""
        with self._lock:
            self._refs[key] = self._refs.get(key, 0) + 1
            self._zero_since.pop(key, None)
        self._bump_disk_refs(key, +1)

    def release(self, key: str) -> int:
        """A worker for this env exited; returns the remaining local count.
        Envs at zero (here AND on disk) become gc-eligible after an idle
        grace period."""
        with self._lock:
            n = max(0, self._refs.get(key, 0) - 1)
            self._refs[key] = n
            if n == 0:
                self._zero_since[key] = time.monotonic()
        self._bump_disk_refs(key, -1)
        return n

    def gc(self, min_idle_s: float = 0.0) -> List[str]:
        """Delete built envs unreferenced (cross-process) for at least
        min_idle_s; returns deleted keys. An instant-delete-at-zero would
        churn envs that the next task reuses, so callers pass a grace."""
        import fcntl

        now = time.monotonic()
        with self._lock:
            dead = [k for k, n in self._refs.items()
                    if n == 0 and now - self._zero_since.get(k, now) >= min_idle_s]
        deleted = []
        for key in dead:
            with self._key_lock(key):
                env_dir = os.path.join(self.base_dir, key)
                if not os.path.exists(env_dir):
                    continue
                with open(os.path.join(self.base_dir, f".{key}.lock"),
                          "w") as lk:
                    fcntl.flock(lk, fcntl.LOCK_EX)
                    try:
                        try:
                            with open(self._refs_path(key)) as f:
                                disk_refs = int(f.read().strip() or 0)
                        except (FileNotFoundError, ValueError):
                            disk_refs = 0
                        with self._lock:
                            local = self._refs.get(key, 0)
                        if disk_refs > 0 or local > 0:
                            continue  # another raylet (or a racing
                            # acquire) still serves this env
                        for plugin in list(_plugins.values()):
                            marker = os.path.join(env_dir,
                                                  f".built.{plugin.name}")
                            if os.path.exists(marker):
                                try:
                                    plugin.delete(env_dir)
                                except Exception:
                                    logger.exception("env delete failed: %s",
                                                     key)
                        shutil.rmtree(env_dir, ignore_errors=True)
                        try:
                            os.unlink(self._refs_path(key))
                        except FileNotFoundError:
                            pass
                        deleted.append(key)
                    finally:
                        fcntl.flock(lk, fcntl.LOCK_UN)
        with self._lock:
            for key in deleted:
                self._refs.pop(key, None)
                self._zero_since.pop(key, None)
        return deleted

    # ------------------------------------------------------------- create
    def python_for(self, runtime_env: dict) -> str:
        """Blocking: the env's python executable (see context_for)."""
        return self.context_for(runtime_env).python

    def context_for(self, runtime_env: dict) -> EnvContext:
        """Blocking: the full worker context (interpreter + plugin env
        vars), running every active plugin's create step on first use.
        Raises RuntimeError on (possibly cached) failure."""
        import fcntl

        key = env_key(runtime_env)
        assert key is not None
        if runtime_env.get("pip") and runtime_env.get("conda"):
            # both want to own the interpreter; the reference rejects the
            # combination too
            raise RuntimeError(
                "runtime_env 'pip' and 'conda' are mutually exclusive "
                "(put pip packages inside the conda dependencies instead)")
        if runtime_env.get("container") and (runtime_env.get("pip")
                                             or runtime_env.get("conda")):
            raise RuntimeError(
                "runtime_env 'container' cannot be combined with "
                "'pip'/'conda' — bake the packages into the image "
                "(the reference imposes the same constraint)")
        active = [p for p in _active_plugins(runtime_env) if p.pooled]

        def contexts(env_dir: str) -> EnvContext:
            ctx = EnvContext()
            for p in active:
                try:
                    p.modify_context(runtime_env[p.name], env_dir, ctx)
                except Exception as e:
                    # cache: a broken context is as fatal as a failed build
                    msg = f"runtime env context failed ({p.name}): {e}"
                    self._failed[key] = msg
                    raise RuntimeError(msg) from None
            return ctx

        with self._key_lock(key):
            if key in self._failed:
                raise RuntimeError(self._failed[key])
            env_dir = os.path.join(self.base_dir, key)
            ready = os.path.join(env_dir, ".ready")
            if os.path.exists(ready):
                return contexts(env_dir)
            # cross-process lock: multiple raylets (in-process Cluster or
            # co-hosted nodes) share the base dir — exactly one builds the
            # env, the rest wait and reuse it
            os.makedirs(self.base_dir, exist_ok=True)
            with open(os.path.join(self.base_dir, f".{key}.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                try:
                    if not os.path.exists(ready):
                        for p in active:
                            try:
                                p.create(runtime_env[p.name], env_dir)
                            except Exception as e:
                                msg = (f"runtime env creation failed "
                                       f"({p.name}): {e}")
                                self._failed[key] = msg
                                raise RuntimeError(msg) from None
                            os.makedirs(env_dir, exist_ok=True)
                            with open(os.path.join(
                                    env_dir, f".built.{p.name}"), "w"):
                                pass
                        with open(ready, "w") as f:
                            f.write(json.dumps(
                                {p.name: True for p in active}))
                    return contexts(env_dir)
                finally:
                    fcntl.flock(lk, fcntl.LOCK_UN)