"""Runtime-env manager: venv-backed pip environments for workers.

Equivalent of the reference's runtime-env agent
(`dashboard/modules/runtime_env/runtime_env_agent.py:161` +
`_private/runtime_env/pip.py`): a `pip` runtime env resolves to a cached
virtualenv (created with --system-site-packages so jax/numpy resolve from
the base image — the reference's pip plugin inherits site-packages the same
way), and workers for that env are spawned from the venv's interpreter.
Environments are content-addressed by the normalized spec, created once
under a filesystem lock, and reused across jobs; creation failures are
remembered so queued work fails fast instead of respawning forever.

Lightweight fields (env_vars, working_dir) are applied in-process by the
worker (`core/worker.py _apply_runtime_env`) and need no dedicated pool.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import sys
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

_DEFAULT_BASE = "/tmp/ray_tpu/runtime_envs"


def env_key(runtime_env: Optional[dict]) -> Optional[str]:
    """Stable key for envs that need a dedicated worker pool; None when any
    worker can run the task after in-process env application."""
    if not runtime_env:
        return None
    pip = runtime_env.get("pip")
    mods = runtime_env.get("py_modules")
    if not pip and not mods:
        return None
    if isinstance(pip, dict):  # {"packages": [...]} form
        pip = pip.get("packages", [])
    # py_modules mutate sys.path for the worker's lifetime, so workers are
    # pooled per package set (like pip envs) rather than shared
    spec = {"pip": sorted(str(p) for p in pip or []),
            "py_modules": sorted(
                str(m.get("uri", m) if isinstance(m, dict) else m)
                for m in mods or [])}
    return hashlib.sha1(json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


class RuntimeEnvManager:
    """Creates and caches venvs; thread-safe, one creation per key."""

    def __init__(self, base_dir: str = _DEFAULT_BASE):
        self.base_dir = base_dir
        self._lock = threading.Lock()
        self._locks: Dict[str, threading.Lock] = {}
        self._failed: Dict[str, str] = {}

    def _key_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._locks.setdefault(key, threading.Lock())

    def creation_error(self, key: str) -> Optional[str]:
        return self._failed.get(key)

    def python_for(self, runtime_env: dict) -> str:
        """Blocking: return the env's python executable, creating the venv
        on first use. Raises RuntimeError on (possibly cached) failure."""
        import fcntl
        import sys

        key = env_key(runtime_env)
        assert key is not None
        if not runtime_env.get("pip"):
            # py_modules-only env: dedicated worker pool (sys.path isolation)
            # but no venv — the host interpreter serves it
            return sys.executable
        with self._key_lock(key):
            if key in self._failed:
                raise RuntimeError(self._failed[key])
            env_dir = os.path.join(self.base_dir, key)
            py = os.path.join(env_dir, "bin", "python")
            marker = os.path.join(env_dir, ".ready")
            if os.path.exists(marker):
                return py
            # cross-process lock: multiple raylets (in-process Cluster or
            # co-hosted nodes) share /tmp/ray_tpu/runtime_envs — exactly one
            # builds the env, the rest wait and reuse it
            os.makedirs(self.base_dir, exist_ok=True)
            with open(os.path.join(self.base_dir, f".{key}.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                try:
                    if os.path.exists(marker):
                        return py
                    pip = runtime_env.get("pip")
                    if isinstance(pip, dict):
                        pip = pip.get("packages", [])
                    try:
                        self._create(env_dir, py, [str(p) for p in pip])
                    except Exception as e:
                        msg = f"runtime env creation failed for pip={pip}: {e}"
                        self._failed[key] = msg
                        raise RuntimeError(msg) from None
                    with open(marker, "w") as f:
                        f.write(json.dumps({"pip": pip}))
                    return py
                finally:
                    fcntl.flock(lk, fcntl.LOCK_UN)

    def _create(self, env_dir: str, py: str, pip: list) -> None:
        import sysconfig

        os.makedirs(self.base_dir, exist_ok=True)
        logger.info("creating runtime env at %s (pip=%s)", env_dir, pip)
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", env_dir],
            check=True, capture_output=True)
        # When this process itself runs in a venv, --system-site-packages
        # points at the *base* interpreter, not our parent venv — link the
        # parent's site-packages too (after the env's own dir, so installed
        # packages shadow inherited ones).
        child_purelib = subprocess.run(
            [py, "-c", "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
            check=True, capture_output=True, text=True).stdout.strip()
        parent_purelib = sysconfig.get_paths()["purelib"]
        if parent_purelib != child_purelib:
            with open(os.path.join(child_purelib, "_parent_site.pth"), "w") as f:
                f.write(parent_purelib + "\n")
        if pip:
            r = subprocess.run(
                [py, "-m", "pip", "install", "--no-input", *pip],
                capture_output=True, text=True, timeout=600)
            if r.returncode != 0:
                raise RuntimeError(r.stderr[-2000:])
