"""Scheduling policies: hybrid pack/spread, spread, affinity, PG bundles.

Equivalent of the reference's pluggable policies under
`src/ray/raylet/scheduling/policy/` — notably the hybrid policy
(`hybrid_scheduling_policy.cc:48-170`): score = critical-resource
utilization, truncated to 0 below `scheduler_spread_threshold` (0.5), so
work packs onto the preferred node until half-utilized, then spreads to the
least-utilized feasible node. Bundle placement mirrors
`bundle_scheduling_policy.cc` (STRICT_PACK/PACK/SPREAD/STRICT_SPREAD).

TPU-first extension: nodes carry labels (`tpu_slice`, `tpu_topology`,
`tpu_worker_id`) and `place_bundles` supports slice-aware packing — a
STRICT_PACK group of TPU bundles lands on hosts of one ICI-connected slice
(same `tpu_slice` label), which is the placement that lets XLA collectives
ride ICI instead of DCN.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.core.config import get_config
from ray_tpu.core.task_spec import SchedulingStrategy

EPSILON = 1e-9


@dataclass
class NodeView:
    node_id: bytes
    total: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)

    def is_feasible(self, demand: Dict[str, float]) -> bool:
        """Could this node *ever* run the demand (vs. total)?"""
        return all(self.total.get(r, 0.0) + EPSILON >= q for r, q in demand.items())

    def is_available(self, demand: Dict[str, float]) -> bool:
        return all(self.available.get(r, 0.0) + EPSILON >= q for r, q in demand.items())

    def utilization(self) -> float:
        """Critical-resource utilization (max over resources)."""
        util = 0.0
        for r, tot in self.total.items():
            if tot > 0:
                util = max(util, 1.0 - self.available.get(r, 0.0) / tot)
        return util


class SchedulingPolicy:
    """Scheduling decisions, natively accelerated when the C++ library
    builds (core/native_scheduler.py); the Python paths below remain the
    executable spec and the fallback."""

    def __init__(self):
        self._native = None
        self._native_lock = threading.Lock()
        if os.environ.get("RAY_TPU_NATIVE_SCHEDULER", "1") != "0":
            try:
                from ray_tpu.core.native_scheduler import NativeScheduler

                self._native = NativeScheduler(
                    get_config().scheduler_spread_threshold)
            except Exception:
                self._native = None

    def _native_select(self, nodes: List[NodeView], demand: Dict[str, float],
                       strategy: str, prefer_node: Optional[bytes]):
        # One lock around sync+select: callers (raylet dispatch loop, GCS rpc
        # + health threads) share this policy, and the native node table is
        # stateful between the two calls.
        with self._native_lock:
            self._native.set_spread_threshold(
                get_config().scheduler_spread_threshold)
            self._native.sync_nodes(nodes)
            return self._native.select(demand, strategy, prefer_node)

    def select_node(
        self,
        nodes: List[NodeView],
        demand: Dict[str, float],
        strategy: Optional[SchedulingStrategy] = None,
        prefer_node: Optional[bytes] = None,
        pg_table: Optional[dict] = None,
    ) -> Optional[bytes]:
        strategy = strategy or SchedulingStrategy()

        # Placement-group targeting: run on the node holding the bundle.
        if strategy.placement_group_id is not None and pg_table is not None:
            pg = pg_table.get(strategy.placement_group_id)
            if not pg or not pg.get("placement"):
                return None
            idx = strategy.bundle_index if strategy.bundle_index >= 0 else 0
            if idx >= len(pg["placement"]):
                return None
            return pg["placement"][idx]

        if strategy.node_id is not None:
            for n in nodes:
                if n.node_id == strategy.node_id and (n.is_feasible(demand)):
                    return n.node_id
            if not strategy.soft:
                return None
            if self._native is not None:
                return self._native_select(nodes, demand, "HYBRID", prefer_node)
            return self._hybrid([n for n in nodes if n.is_feasible(demand)],
                                demand, prefer_node)

        if self._native is not None:
            native_strategy = "SPREAD" if strategy.name == "SPREAD" else "HYBRID"
            return self._native_select(nodes, demand, native_strategy,
                                       prefer_node)

        feasible = [n for n in nodes if n.is_feasible(demand)]
        if not feasible:
            return None

        if strategy.name == "SPREAD":
            avail = [n for n in feasible if n.is_available(demand)] or feasible
            return min(avail, key=lambda n: (n.utilization(), n.node_id)).node_id

        return self._hybrid(feasible, demand, prefer_node)

    def _hybrid(self, feasible: List[NodeView], demand: Dict[str, float],
                prefer_node: Optional[bytes]) -> Optional[bytes]:
        if not feasible:
            return None
        threshold = get_config().scheduler_spread_threshold

        def score(n: NodeView):
            util = n.utilization()
            truncated = 0.0 if util < threshold else util
            # Prefer nodes that can run it *now*; among them the preferred
            # (usually local) node wins ties, mirroring the reference's
            # top-k-with-local-preference ordering.
            unavailable = 0 if n.is_available(demand) else 1
            not_preferred = 0 if n.node_id == prefer_node else 1
            return (unavailable, truncated, not_preferred, n.node_id)

        return min(feasible, key=score).node_id

    # ---------------------------------------------------------- PG bundles
    def place_bundles(
        self,
        nodes: List[NodeView],
        bundles: List[Dict[str, float]],
        strategy: str,
    ) -> Optional[List[bytes]]:
        """Return a node id per bundle, or None if infeasible."""
        if strategy not in ("STRICT_PACK", "PACK", "STRICT_SPREAD", "SPREAD"):
            raise ValueError(f"unknown placement strategy {strategy}")
        if self._native is not None:
            try:
                with self._native_lock:
                    self._native.sync_nodes(nodes)
                    return self._native.place_bundles(bundles, strategy)
            except RuntimeError:
                pass  # e.g. output-buffer overflow on huge placements
        if strategy in ("STRICT_PACK", "PACK"):
            return self._pack(nodes, bundles, strict=(strategy == "STRICT_PACK"))
        return self._spread(nodes, bundles, strict=(strategy == "STRICT_SPREAD"))

    def _pack(self, nodes: List[NodeView], bundles, strict: bool) -> Optional[List[bytes]]:
        # TPU slice-awareness: try to satisfy all bundles within one slice's
        # hosts first (same tpu_slice label), then any single node (strict),
        # then first-fit-decreasing across nodes (non-strict).
        slices: Dict[str, List[NodeView]] = {}
        for n in nodes:
            s = n.labels.get("tpu_slice")
            if s:
                slices.setdefault(s, []).append(n)
        candidate_groups = list(slices.values())
        if strict:
            candidate_groups = [[n] for n in nodes] + candidate_groups
        else:
            candidate_groups = candidate_groups + [nodes]
        for group in candidate_groups:
            placement = self._first_fit(group, bundles)
            if placement is not None:
                return placement
        return None if strict else self._first_fit(nodes, bundles)

    def _spread(self, nodes: List[NodeView], bundles, strict: bool) -> Optional[List[bytes]]:
        remaining = {n.node_id: dict(n.available) for n in nodes}
        order = sorted(nodes, key=lambda n: (n.utilization(), n.node_id))
        placement: List[bytes] = []
        used: set = set()
        for b in bundles:
            chosen = None
            for n in order:
                if strict and n.node_id in used:
                    continue
                if all(remaining[n.node_id].get(r, 0.0) + EPSILON >= q for r, q in b.items()):
                    chosen = n.node_id
                    break
            if chosen is None:
                if strict:
                    return None
                # fall back to any feasible node
                for n in order:
                    if all(remaining[n.node_id].get(r, 0.0) + EPSILON >= q for r, q in b.items()):
                        chosen = n.node_id
                        break
                if chosen is None:
                    return None
            for r, q in b.items():
                remaining[chosen][r] = remaining[chosen].get(r, 0.0) - q
            used.add(chosen)
            placement.append(chosen)
            # re-sort so spreading stays balanced
            order = sorted(order, key=lambda n: (1.0 - min(
                (remaining[n.node_id].get(r, 0.0) / t if t else 1.0)
                for r, t in (n.total.items() if n.total else [("CPU", 1.0)])), n.node_id))
        return placement

    @staticmethod
    def _first_fit(group: List[NodeView], bundles) -> Optional[List[bytes]]:
        remaining = {n.node_id: dict(n.available) for n in group}
        placement: List[bytes] = []
        for b in bundles:
            chosen = None
            for n in group:
                if all(remaining[n.node_id].get(r, 0.0) + EPSILON >= q for r, q in b.items()):
                    chosen = n.node_id
                    break
            if chosen is None:
                return None
            for r, q in b.items():
                remaining[chosen][r] = remaining[chosen].get(r, 0.0) - q
            placement.append(chosen)
        return placement
