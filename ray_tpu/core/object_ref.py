"""ObjectRef: a future-like handle to an object in the cluster.

Mirrors the reference's `python/ray/includes/object_ref.pxi` ObjectRef:
hashable, comparable, awaitable via `get()`, and pickling one registers a
borrow with the serialization context so the ownership layer can track
nested/borrowed references (reference `reference_count.h:220`).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    # _counted: this instance holds one unit of the distributed refcount and
    # releases it on GC (reference RemoveLocalReference). Only instances
    # created through a counting path (task returns, put, deserialization)
    # set it; ad-hoc internal ObjectRef(...) constructions never release.
    __slots__ = ("id", "owner_address", "_call_site", "_counted")

    def __init__(self, object_id: ObjectID, owner_address: Optional[str] = None, call_site: str = ""):
        self.id = object_id
        self.owner_address = owner_address
        self._call_site = call_site
        self._counted = False

    def __del__(self):
        if not getattr(self, "_counted", False):
            return
        try:
            from ray_tpu.core import worker as _worker_mod

            w = _worker_mod.current_worker()
            if w is not None and not w._shutdown.is_set():
                w.reference_counter.remove_local(self)
        except Exception:
            pass  # interpreter teardown

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Record the borrow (no-op outside an active serialize()).
        from ray_tpu.core import serialization

        serialization.record_contained_ref(self)
        return (_rebuild_ref, (self.id, self.owner_address, self._call_site))

    def future(self):
        """Return a concurrent.futures.Future resolving to the object value."""
        from ray_tpu.core.api import _global_worker
        return _global_worker().get_async(self)


class ObjectRefGenerator:
    """Iterator over the ObjectRefs streamed out of a num_returns="dynamic"
    task (reference ObjectRefGenerator, _raylet.pyx:178,997).

    On the task's OWNER it streams: each __next__ blocks until the executor
    reports the next yielded object (or the task finishes/fails), so items
    are consumable while the task still runs. Serialized (e.g. nested in a
    return value or fetched by a borrower) it carries the final ref list —
    borrowers iterate the completed sequence."""

    def __init__(self, refs=None, task_id=None, done: bool = True):
        self._refs = list(refs or [])
        self._task_id = task_id
        self._done = done
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        if self._done:
            if self._i >= len(self._refs):
                raise StopIteration
            r = self._refs[self._i]
            self._i += 1
            return r
        from ray_tpu.core import worker as _worker_mod

        w = _worker_mod.current_worker()
        ref, done, err = w.next_dynamic_return(self._task_id, self._i)
        if ref is not None:
            self._refs.append(ref)
            self._i += 1
            return ref
        self._done = True
        if err is not None:
            raise err
        raise StopIteration

    def __len__(self):
        if not self._done:
            raise TypeError("streaming generator has no length until consumed")
        return len(self._refs)

    def completed_refs(self):
        """Refs yielded so far (all of them once done)."""
        return list(self._refs)

    def __reduce__(self):
        if not self._done:
            raise TypeError(
                "a streaming ObjectRefGenerator can only be serialized "
                "after the task completes; iterate it (or pass individual "
                "item refs) instead")
        # pickling the refs records the contained-ref borrows (ObjectRef
        # __reduce__), so a generator nested in a stored object keeps its
        # items alive for the container's lifetime
        return (_rebuild_generator, (list(self._refs),))


def _rebuild_generator(refs):
    return ObjectRefGenerator(refs, done=True)


def _rebuild_ref(object_id, owner_address, call_site):
    ref = ObjectRef(object_id, owner_address, call_site)
    # Register the materialized instance with the ownership layer: borrowed
    # (+notify owner) off-owner, a plain local ref on the owner. Either way
    # this instance now holds one refcount unit and releases it on GC.
    from ray_tpu.core import worker as _worker_mod

    w = _worker_mod.current_worker()
    if w is not None:
        if owner_address and owner_address == w.address:
            w.add_local_ref(object_id)
        else:
            w.reference_counter.add_borrowed(ref)
        ref._counted = True
    return ref
