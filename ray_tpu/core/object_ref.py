"""ObjectRef: a future-like handle to an object in the cluster.

Mirrors the reference's `python/ray/includes/object_ref.pxi` ObjectRef:
hashable, comparable, awaitable via `get()`, and pickling one registers a
borrow with the serialization context so the ownership layer can track
nested/borrowed references (reference `reference_count.h:220`).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    # _counted: this instance holds one unit of the distributed refcount and
    # releases it on GC (reference RemoveLocalReference). Only instances
    # created through a counting path (task returns, put, deserialization)
    # set it; ad-hoc internal ObjectRef(...) constructions never release.
    __slots__ = ("id", "owner_address", "_call_site", "_counted")

    def __init__(self, object_id: ObjectID, owner_address: Optional[str] = None, call_site: str = ""):
        self.id = object_id
        self.owner_address = owner_address
        self._call_site = call_site
        self._counted = False

    def __del__(self):
        if not getattr(self, "_counted", False):
            return
        try:
            from ray_tpu.core import worker as _worker_mod

            w = _worker_mod.current_worker()
            if w is not None and not w._shutdown.is_set():
                w.reference_counter.remove_local(self)
        except Exception:
            pass  # interpreter teardown

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        # Record the borrow (no-op outside an active serialize()).
        from ray_tpu.core import serialization

        serialization.record_contained_ref(self)
        return (_rebuild_ref, (self.id, self.owner_address, self._call_site))

    def future(self):
        """Return a concurrent.futures.Future resolving to the object value."""
        from ray_tpu.core.api import _global_worker
        return _global_worker().get_async(self)


def _rebuild_ref(object_id, owner_address, call_site):
    ref = ObjectRef(object_id, owner_address, call_site)
    # Register the materialized instance with the ownership layer: borrowed
    # (+notify owner) off-owner, a plain local ref on the owner. Either way
    # this instance now holds one refcount unit and releases it on GC.
    from ray_tpu.core import worker as _worker_mod

    w = _worker_mod.current_worker()
    if w is not None:
        if owner_address and owner_address == w.address:
            w.add_local_ref(object_id)
        else:
            w.reference_counter.add_borrowed(ref)
        ref._counted = True
    return ref
