"""CoreWorker: per-process runtime for drivers and workers.

Equivalent of the reference's `CoreWorker` (`src/ray/core_worker/
core_worker.h:284`) + its Cython binding (`python/ray/_raylet.pyx:1730`):
task submission, the ownership table with reference counting
(`reference_count.h:61` — semantics re-implemented, not translated), object
put/get against the two-tier store, task retries, the direct actor transport
(per-caller sequence numbers, `transport/sequential_actor_submit_queue.h`),
and the execution loop that runs user functions in worker processes
(`_raylet.pyx:718 execute_task`).

Every process (driver or worker) hosts a core-worker RPC server; results are
pushed directly from executor to owner (ownership-based result routing), and
borrowers talk to owners for locations — raylets only handle scheduling and
the node-local object store.
"""

from __future__ import annotations

import asyncio
import heapq
import inspect
import logging
import os
import queue
import random
import threading
import time
import traceback
import weakref
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import rpc, serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    ObjectStoreFullError,
    OwnerDiedError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.function_table import FunctionTableClient
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID, _TaskIDCounter
from ray_tpu.core.task_events import TaskEventBuffer
from ray_tpu.util import tracing
from ray_tpu.core.object_store import attach_object
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.core.task_spec import (
    ActorCreationSpec,
    TaskSpec,
    TaskType,
)

logger = logging.getLogger(__name__)

_current_worker: Optional["CoreWorker"] = None
_worker_lock = threading.Lock()


def current_worker() -> Optional["CoreWorker"]:
    return _current_worker


def set_current_worker(w: Optional["CoreWorker"]) -> None:
    global _current_worker
    with _worker_lock:
        _current_worker = w


def _send_unpin(worker_ref, oid) -> None:
    """weakref.finalize target for zero-copy reader views: module-level so
    the finalizer holds no strong reference to the worker — a leaked view
    must never keep a shut-down CoreWorker (and its sockets) alive."""
    w = worker_ref()
    if w is None or w._shutdown.is_set():
        return  # raylet-side conn-close reaping covers this case
    try:
        w.raylet.notify("obj_unpin", {"object_id": oid})
    except Exception:
        pass  # raylet gone: its store died with it


# ---------------------------------------------------------------------------


@dataclass
class _ObjectState:
    """Owner-side record for one owned object."""

    state: str = "pending"          # pending | inline | plasma | error
    inline_blob: Optional[bytes] = None
    location: Optional[str] = None  # raylet address holding the primary copy
    extra_locations: List[str] = field(default_factory=list)  # pulled copies
    size: int = 0
    # (segment_name, attach_size) of the primary copy at `location`: lets a
    # co-located reader attach the shm segment directly — no pull_object
    # round-trip (stale after spill/restore; readers fall back and re-learn)
    segment: Optional[Tuple[str, int]] = None
    local_refs: int = 0
    borrowers: int = 0
    submitted_task_deps: int = 0    # in-flight tasks depending on this object
    shipped: bool = False           # a ref to this object was serialized out
    container_pinned: int = 0       # live owned containers holding our ref
    contained_pins: List["ObjectID"] = field(default_factory=list)  # inner oids we pin
    contained_borrows: List = field(default_factory=list)  # counted refs we borrow
    free_after: Optional[float] = None  # deferred-free deadline (monotonic)
    waiters: List[Tuple] = field(default_factory=list)  # (conn, req_id) info waiters
    callbacks: List[Callable] = field(default_factory=list)  # done callbacks


class ReferenceCounter:
    """Ownership + borrowed reference tracking (reference semantics of
    `src/ray/core_worker/reference_count.h`). Borrows are registered with
    the owner at deserialization time over a per-owner reconnecting link
    and are CONNECTION-SCOPED on the owner (a dead borrower's dropped link
    releases them — the reference's WaitForRefRemoved liveness role — and
    a reconnect replays live borrows). Transitive borrowers register with
    the owner directly rather than through per-hop borrow tables."""

    def __init__(self, worker: "CoreWorker"):
        self._worker = worker
        self._borrowed: Dict[ObjectID, dict] = {}
        # one reconnecting link per owner: borrow registrations ride it, and
        # on every fresh connection the live borrows are REPLAYED — so a
        # transient drop (which the owner treats as borrower death and
        # releases) re-establishes the borrow instead of silently losing it
        self._owner_links: Dict[str, rpc.ReconnectingClient] = {}
        self._lock = threading.RLock()

    def owner_link(self, owner: str) -> rpc.ReconnectingClient:
        with self._lock:
            link = self._owner_links.get(owner)
            if link is None or link.closed:
                link = rpc.ReconnectingClient(
                    owner,
                    on_reconnect=lambda raw, o=owner: self._replay_borrows(o, raw),
                    origin=self._worker.raylet_address)
                self._owner_links[owner] = link
            return link

    def _replay_borrows(self, owner: str, raw: "rpc.RpcClient") -> None:
        with self._lock:
            oids = [oid for oid, e in self._borrowed.items()
                    if e["owner"] == owner and e["count"] > 0]
        for oid in oids:
            raw.notify("add_borrower", {"object_id": oid})

    def close(self) -> None:
        with self._lock:
            links, self._owner_links = list(self._owner_links.values()), {}
        for link in links:
            link.close()

    def add_borrowed(self, ref: ObjectRef) -> None:
        w = self._worker
        if ref.owner_address == w.address:
            return  # we own it
        with self._lock:
            e = self._borrowed.get(ref.id)
            if e is None:
                self._borrowed[ref.id] = {"count": 1, "owner": ref.owner_address, "registered": False}
                self._register_borrow(ref)
            else:
                e["count"] += 1

    def _register_borrow(self, ref: ObjectRef) -> None:
        if not ref.owner_address:
            return
        try:
            self.owner_link(ref.owner_address).notify(
                "add_borrower", {"object_id": ref.id})
            self._borrowed[ref.id]["registered"] = True
        except Exception:
            # NOT silent: an unregistered borrow leaves only the owner's
            # free-grace window protecting the object; the reconnect replay
            # re-attempts, and lineage recovery backstops the loss
            logger.debug("borrow registration for %s with %s failed",
                         ref.id, ref.owner_address, exc_info=True)

    def remove_local(self, ref: ObjectRef) -> None:
        # The full decrement/pop happens under the lock; only the (idempotent)
        # owner notification runs outside it, so concurrent removers can never
        # interleave on the same entry (reference holds its mutex across the
        # whole RemoveLocalReference body, reference_count.h:109).
        notify_owner = None
        with self._lock:
            e = self._borrowed.get(ref.id)
            if e is not None:
                e["count"] -= 1
                if e["count"] <= 0:
                    self._borrowed.pop(ref.id, None)
                    if e.get("registered"):
                        notify_owner = e["owner"]
        if e is None:
            self._worker._remove_owned_local_ref(ref.id)
        elif notify_owner is not None:
            # Off-thread: remove_local runs from ObjectRef.__del__, and
            # peer() can block up to rpc_connect_timeout_s reconnecting to a
            # dead owner — never stall whatever thread triggered the GC.
            self._worker._notify_owner_async(
                notify_owner, "remove_borrower", {"object_id": ref.id})


# ---------------------------------------------------------------------------


class CoreWorker:
    def __init__(
        self,
        mode: str,                       # "driver" | "worker"
        raylet_address: str,
        gcs_address: str,
        job_id: Optional[JobID] = None,
        host: str = "127.0.0.1",
        connect_timeout: Optional[float] = None,
        log_to_driver: bool = True,
    ):
        self.mode = mode
        self.log_to_driver = log_to_driver
        self.worker_id = WorkerID.from_random()
        self.job_id = job_id or JobID.from_random()
        self.raylet_address = raylet_address
        self.gcs_address = gcs_address

        self._server = rpc.RpcServer(host)
        self._server.register_all(self)
        self._server.start()

        self.reference_counter = ReferenceCounter(self)
        self._objects: Dict[ObjectID, _ObjectState] = {}
        self._obj_lock = threading.RLock()
        self._obj_cv = threading.Condition(self._obj_lock)

        # Lineage table (cf. reference object_recovery_manager.h:41): the
        # creating TaskSpec of every owned task output, retained even after
        # the object's data is freed so a lost primary can be recomputed.
        # Insertion-ordered; FIFO-evicted at lineage_table_max_entries.
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._lineage_attempts: Dict[TaskID, int] = {}
        # per-task record of arg pins actually taken (guarded by _obj_lock)
        self._task_pins: Dict[TaskID, List[ObjectID]] = {}
        # application pubsub subscriptions (channel -> callbacks)
        self._channel_callbacks: Dict[str, List[Callable]] = {}
        self._channel_cb_lock = threading.Lock()
        # streaming (num_returns="dynamic") tasks we own: task id ->
        # {"refs": [ObjectRef...], "done": bool, "error": Exception|None}
        # (guarded by _obj_lock; _obj_cv signals arrivals)
        self._dynamic_returns: Dict[TaskID, dict] = {}
        # dynamic return ids with lineage entries, for whole-task eviction
        self._task_dynamic_ids: Dict[TaskID, List[ObjectID]] = {}

        # borrows keyed by the borrower's server connection (see
        # rpc_add_borrower): conn id -> {object_id: count}
        self._conn_borrows: Dict[int, Dict[ObjectID, int]] = {}
        # objects whose local pulled copy we already announced to the owner
        from collections import OrderedDict

        self._registered_copies: "OrderedDict[ObjectID, bool]" = OrderedDict()
        self._registered_copies_lock = threading.Lock()
        # zero-copy object plane: worker-side location cache of local
        # (segment_name, attach_size) per object — repeat gets of a hot
        # object skip owner resolution AND pull_object entirely (validated
        # by the pin-confirm protocol, so a stale entry can only cost a
        # fallback, never wrong data)
        self._seg_cache: "OrderedDict[ObjectID, Tuple[str, int]]" = OrderedDict()
        self._seg_cache_lock = threading.Lock()
        # writer-side mapping cache: segment name -> persistent writable
        # mmap. The store's reuse pool hands the same segments back to hot
        # writers; writing through a mapping whose page tables are already
        # populated runs at memory bandwidth (~2x the writev path, ~10x a
        # fresh mapping's zero-fault+copy). Bounded LRU (entries + bytes).
        self._write_maps: "OrderedDict[str, Any]" = OrderedDict()
        self._write_maps_bytes = 0
        self._write_maps_lock = threading.Lock()
        # shared outstanding wait-futures: (owner, oid) -> Future (LRU-capped)
        self._wait_futures: "OrderedDict[tuple, Any]" = OrderedDict()
        self._wait_futures_lock = threading.Lock()

        # grace-deferred plasma frees (see _maybe_free)
        self._deferred_frees: deque = deque()
        self._free_sweeper: Optional[threading.Thread] = None
        # background owner notifications (ref releases from __del__)
        self._owner_notify_q: "queue.Queue[Tuple[str, str, dict]]" = queue.Queue()
        self._owner_notify_thread: Optional[threading.Thread] = None
        self._owner_notify_lock = threading.Lock()

        self._task_counter = _TaskIDCounter(self.worker_id)
        self._put_counter = 0
        self._put_lock = threading.Lock()
        # Root task id for the process; per-execution-thread ids live in TLS
        # so concurrent actor methods attribute puts correctly.
        self._root_task_id = TaskID(self.worker_id.binary())
        self._tls = threading.local()

        self._peers: Dict[str, rpc.RpcClient] = {}
        self._peers_lock = threading.Lock()

        # Delayed resubmits (task retries) ride ONE shared timer thread
        # instead of one threading.Timer per retry: a burst of failed tasks
        # must not fork hundreds of timer threads. Heap of
        # (due_monotonic, seq, spec); seq breaks ties (specs don't compare).
        self._resubmit_heap: list = []
        self._resubmit_cv = threading.Condition()
        self._resubmit_thread: Optional[threading.Thread] = None
        self._resubmit_seq = 0

        # pending task specs for retry: task_id -> [spec, retries_left].
        # Touched by user threads (submit), the RPC reader (results, death
        # notifications) and the GCS push thread (actor death fan-out), so all
        # compound read-modify-write goes through _pending_lock.
        self._pending_tasks: Dict[TaskID, list] = {}
        self._pending_lock = threading.Lock()
        # node-level failure domain: last known node (binary id) a pending
        # task was spilled to. A raylet that spills a task notifies the
        # owner (rpc_task_spilled); when the GCS announces that node's death
        # on the nodes channel — or a post-reconnect reconciliation finds it
        # gone — the owner fails the task over exactly as if the raylet had
        # pushed task_worker_died (the raylet is dead and never will).
        # Guarded by _pending_lock; entries die with their pending entry.
        self._task_locations: Dict[TaskID, bytes] = {}
        # workers subscribe to the nodes channel LAZILY, on their first
        # spill notification — most (and every warm-forked) worker never
        # owns a spilled task, and an eager subscribe would put a blocking
        # GCS RPC + a permanent fan-out target on the ~1 ms fork hot path.
        # Drivers subscribe eagerly at registration. Guarded by
        # _pending_lock.
        self._nodes_subscribed = False
        # two-strike absence tracking for the post-reconnect reconciliation:
        # a node missing from get_all_nodes may simply not have re-registered
        # yet, so only a node absent across two spaced checks fails over.
        self._absent_nodes: set = set()

        # --- cancellation (job failure domain) ---
        # Owner side: ids cancel() claimed while the task was still pending.
        # Makes double-cancel idempotent, suppresses every retry path, and
        # demotes a LATE success report to the typed error so a cancelled
        # ref resolves deterministically. Guarded by _pending_lock.
        self._cancelled_tasks: Dict[TaskID, float] = {}
        # Executor side: ids cancelled before/while queued in THIS process
        # (the actor-mailbox purge — _execute_task raises instead of
        # running them) + the thread currently executing each task (the
        # cooperative-interrupt injection target). Own lock: cancel pushes
        # arrive on RPC reader threads while exec threads mutate the map.
        self._cancel_lock = threading.Lock()
        self._cancelled_exec: set = set()
        self._exec_thread_ids: Dict[TaskID, int] = {}

        # actor state (when this worker hosts an actor)
        self.actor_id: Optional[ActorID] = None
        self._actor_instance: Any = None
        self._actor_creation_spec: Optional[ActorCreationSpec] = None
        # the incarnation THIS process instantiates (GCS-stamped restart
        # count at dispatch): replies carry it, and calls resolved against
        # a different incarnation are refused (partition failure domain —
        # a superseded instance must never service a call)
        self._actor_incarnation: int = 0
        self._actor_seq_lock = threading.Lock()
        self._actor_next_seq: Dict[bytes, int] = {}       # caller -> expected seq
        self._actor_ooo_buffer: Dict[bytes, Dict[int, TaskSpec]] = {}

        # actor submission (when this worker calls actors)
        self._actor_seq_counters: Dict[ActorID, int] = {}
        self._actor_addresses: Dict[ActorID, str] = {}
        # incarnation the address above was learned WITH: stamped into
        # every outgoing actor task so the target can fence a stale handle
        # (or discover it is itself superseded)
        self._actor_incarnations: Dict[ActorID, int] = {}
        self._actor_dead: Dict[ActorID, str] = {}
        self._actor_cv = threading.Condition()  # pubsub wakes address waits
        # fenced-call resends (target refused our incarnation): bounded per
        # task so a confused topology can't ping-pong a call forever
        self._fence_resends: Dict[TaskID, int] = {}
        # late replies dropped for carrying a superseded incarnation
        self.stale_reply_rejections = 0

        # execution
        self._registered = threading.Event()
        self._task_queue: "queue.Queue[TaskSpec]" = queue.Queue()
        # actor concurrency groups: name -> dedicated queue (reference
        # actor.py:65; threads started in _init_actor)
        self._group_queues: Dict[str, "queue.Queue[TaskSpec]"] = {}
        # default-pool threads (group pools track nothing: their threads
        # are daemons sized once at creation)
        self._default_exec_threads: List[threading.Thread] = []
        self._executing_count = 0
        self._fn_call_counts: Dict[int, int] = {}
        # chip indices granted by the raylet (get_tpu_ids surface)
        self._task_tpu_ids: Dict[TaskID, List[int]] = {}
        # tracing: raylet dispatch stamps awaiting execution (epoch us)
        self._task_dispatch_us: Dict[TaskID, float] = {}
        self._actor_tpu_ids: List[int] = []
        # executing+queued actor tasks excluding control-plane probes, so a
        # load reading is never inflated by the health checks that sample it
        self._load_count = 0
        self._exec_count_lock = threading.Lock()
        self._exec_threads_lock = threading.Lock()
        self._shutdown = threading.Event()
        # optional submission-side instrumentation: called with each
        # outgoing TaskSpec (microbenchmark wire-bytes probe); None = off
        self._spec_bytes_probe = None

        # origin = OUR RAYLET's address: workers and drivers belong to
        # their node for partition purposes, so cutting a node group also
        # blackholes its workers' control-plane and peer traffic
        self.raylet = rpc.connect_with_retry(
            raylet_address, push_handler=self._on_raylet_push,
            timeout=connect_timeout or get_config().rpc_connect_timeout_s,
            origin=raylet_address)
        # Reconnecting control-plane link: survives a GCS restart by
        # re-registering this process's durable facts (job, subscriptions,
        # hosted actor) on every fresh connection. The resolver follows a
        # REPLACEMENT head to a new address: the address file when
        # configured, else this node's raylet (whose own reconnect loop
        # tracks the head) answers get_gcs_address.
        self.gcs = rpc.ReconnectingClient(
            gcs_address, push_handler=self._on_gcs_push,
            on_reconnect=self._replay_gcs_state,
            resolve=self._resolve_gcs_address,
            origin=raylet_address)

        # task-path fast lanes: export-once function table + batched
        # task-event/profile shipping (both ride self.gcs)
        self.function_table = FunctionTableClient(self)
        self.task_events = TaskEventBuffer(self)
        # completion-path fast lane: per-owner batched result delivery
        from ray_tpu.core.result_buffer import ResultBuffer

        self.result_buffer = ResultBuffer(self)

        # Visible to task code before the first task can possibly arrive.
        set_current_worker(self)

        self.node_id: bytes = b""
        reply = self.raylet.call("register_worker", {
            "worker_id": self.worker_id,
            "worker_type": mode,
            "address": self._server.address,
            "pid": os.getpid(),
            "env_key": os.environ.get("RAY_TPU_RUNTIME_ENV_KEY"),
            # set by worker_pool._forked_child_main: this process was forked
            # from a warm template rather than cold-spawned
            "forked": os.environ.get("RAY_TPU_WORKER_FORKED") == "1",
        })
        self.node_id = reply["node_id"]
        self._registered.set()

        if mode == "worker":
            self._start_exec_threads(1)

        if mode == "driver":
            self.gcs.call("register_job", {
                "job_id": self.job_id.binary(),
                "driver_address": self._server.address,
            })
            # "nodes" rides along: node death is an OWNER-side failure
            # signal — a task spilled to a raylet that dies whole-node has
            # nobody left to push task_worker_died, so the owner reacts to
            # the GCS membership event instead.
            channels = ["actors", "nodes"]
            if self.log_to_driver:
                channels.append("logs")
            self.gcs.call("subscribe", {"channels": channels,
                                        "origin": self.raylet_address})
            with self._pending_lock:
                self._nodes_subscribed = True
        # workers own the subtasks they submit and get the same node-death
        # signal, but subscribe lazily on their first spill notification
        # (_ensure_nodes_subscribed) — see _nodes_subscribed.

    # ------------------------------------------------------------------ util
    @property
    def address(self) -> str:
        return self._server.address

    def peer(self, address: str,
             connect_timeout_s: Optional[float] = None) -> rpc.RpcClient:
        """Cached connection to another worker/raylet. The dial happens
        OUTSIDE the cache lock: connect_with_retry spins for the full
        connect timeout when the target is dead (SIGKILLed worker whose
        address we still hold), and holding the lock through that would
        serialize every other peer() caller in the process behind one
        corpse — under a node kill storm that stalls submissions to
        perfectly healthy actors for 30 s at a time."""
        with self._peers_lock:
            c = self._peers.get(address)
            if c is not None and not c.closed:
                return c
        c = rpc.connect_with_retry(
            address,
            timeout=connect_timeout_s or get_config().rpc_connect_timeout_s,
            origin=self.raylet_address)
        with self._peers_lock:
            existing = self._peers.get(address)
            if existing is not None and not existing.closed:
                # a concurrent dial won the install race: use the shared
                # client, drop ours
                c.close()
                return existing
            self._peers[address] = c
            return c

    def shutdown(self) -> None:
        self._shutdown.set()
        # final buffer flushes BEFORE the links close: a clean exit may not
        # lose buffered results or lifecycle events (the at-shutdown half of
        # the batching contract)
        self.result_buffer.stop()
        self.task_events.stop()
        self.reference_counter.close()
        if self.mode == "driver":
            try:
                self.gcs.call("mark_job_finished", {"job_id": self.job_id.binary()}, timeout=2)
            except (OSError, TimeoutError, rpc.RpcDisconnected) as e:
                logger.debug("mark_job_finished lost at shutdown: %s", e)
        for c in list(self._peers.values()):
            c.close()
        try:
            self.raylet.close()
        except OSError:
            pass  # connection already dead
        try:
            self.gcs.close()
        except OSError:
            pass  # connection already dead
        self._server.stop()

    # ------------------------------------------------------------ submission
    def submit_task(
        self,
        func: Callable,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        scheduling=None,
        max_retries: int = 0,
        retry_exceptions: bool = False,
        runtime_env: Optional[dict] = None,
        max_calls: int = 0,
    ) -> List[ObjectRef]:
        from ray_tpu.core.task_spec import SchedulingStrategy

        if runtime_env and runtime_env.get("py_modules"):
            from ray_tpu.runtime_env import upload_py_modules

            runtime_env = upload_py_modules(runtime_env, self.gcs)
        task_id = self._task_counter.next_task_id()
        # Export-once fast lane: first submission of a callable pickles it
        # once and exports the blob to the GCS function table; afterwards
        # the spec carries only the 16-byte content hash (the fallback
        # ships the blob inline for unexportable one-shot callables).
        function_id, function_blob = self.function_table.export(func)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.NORMAL,
            function_blob=function_blob,
            function_id=function_id,
            method_name=getattr(func, "__name__", "anonymous"),
            args=self._serialize_args(args, task_id),
            kwargs_blob=serialization.dumps(kwargs) if kwargs else None,
            num_returns=num_returns,
            resources=dict(resources or {}),
            scheduling=scheduling or SchedulingStrategy(),
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            owner_address=self.address,
            owner_worker_id=self.worker_id,
            runtime_env=runtime_env,
            max_calls=max_calls,
            parent_task_id=self._parent_for_submit(),
        )
        t_sub = self._stamp_trace_ctx(spec)
        refs = self._register_returns(spec)
        with self._pending_lock:
            self._pending_tasks[task_id] = [spec, max_retries]
        self._emit_task_event(spec, "SUBMITTED")
        probe = self._spec_bytes_probe
        if probe is not None:
            try:
                probe(spec)
            except Exception:
                logger.debug("spec bytes probe failed", exc_info=True)
        self.raylet.notify("submit_task", {"spec": spec})
        self._record_submit_span(spec, t_sub)
        return refs

    def flush_profile_events(self) -> None:
        """Force-flush this process's event buffer (task events + tracing
        spans) to the GCS so `timeline()` on any driver aggregates
        cluster-wide events NOW instead of at the next batch interval
        (reference ProfileEvent -> TaskEventBuffer -> GCS)."""
        self.task_events.flush()

    def _emit_task_event(self, spec: TaskSpec, state: str) -> None:
        """Best-effort task lifecycle record, coalesced in the worker-side
        TaskEventBuffer and shipped on its flush timer (reference
        TaskEventBuffer -> GcsTaskManager)."""
        try:
            self.task_events.record(spec, state)
        except Exception:
            logger.debug("task event record failed", exc_info=True)

    def _stamp_trace_ctx(self, spec: TaskSpec) -> float:
        """Tracing-enabled only: mint the submit-stage span id and stamp
        (trace_id, submit span_id) into the spec BEFORE it serializes, so
        the raylet's lease span and the executor's run/result spans parent
        under this submission. Returns the submit-span start stamp (0.0
        when tracing is off — the hot path pays one config read)."""
        if not tracing.enabled():
            return 0.0
        ctx = tracing.current_ctx()
        # no ambient trace -> this submission roots its own (detached: the
        # thread's TLS stays clean so unrelated submissions don't coalesce
        # into one giant trace)
        trace_id = ctx[0] if ctx else tracing.new_id()
        spec.trace_ctx = (trace_id, tracing.new_id())
        return tracing.now_us()

    def _record_submit_span(self, spec: TaskSpec, t_sub: float) -> None:
        if spec.trace_ctx is None or not t_sub:
            return
        parent = tracing.current_ctx()
        tracing.add_complete(
            f"submit::{spec.method_name}", "task_submit",
            t_sub, tracing.now_us() - t_sub,
            trace_id=spec.trace_ctx[0], span_id=spec.trace_ctx[1],
            parent_id=parent[1] if parent else "",
            task_id=spec.task_id.binary().hex())

    def _register_returns(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = []
        cfg = get_config()
        with self._obj_lock:
            for oid in spec.return_object_ids():
                st = self._objects.get(oid)
                if st is None:
                    st = _ObjectState()
                    self._objects[oid] = st
                st.state = "pending"
                st.local_refs += 1
                r = ObjectRef(oid, owner_address=self.address)
                r._counted = True
                refs.append(r)
                if spec.task_type == TaskType.NORMAL:
                    self._lineage[oid] = spec
            if spec.num_returns == -1:
                self._dynamic_returns[spec.task_id] = {
                    "refs": [], "done": False, "error": None}
            while len(self._lineage) > cfg.lineage_table_max_entries:
                # Evict a whole task's returns together and drop its retry
                # counter so _lineage_attempts can't grow unboundedly.
                old = self._lineage.pop(next(iter(self._lineage)))
                for roid in old.return_object_ids():
                    self._lineage.pop(roid, None)
                for roid in self._task_dynamic_ids.pop(old.task_id, ()):
                    self._lineage.pop(roid, None)
                self._lineage_attempts.pop(old.task_id, None)
        return refs

    def _serialize_args(self, args: tuple,
                        task_id: Optional[TaskID] = None) -> List[Tuple]:
        """Inline small values; pass refs through; promote big args to the
        object store (cf. reference: big args -> plasma `Put`)."""
        out: List[Tuple] = []
        cfg = get_config()
        for a in args:
            if isinstance(a, ObjectRef):
                out.append(("ref", a.id, a.owner_address))
                self._pin_for_submission(a, task_id)
            else:
                s = serialization.serialize(a)
                self._mark_shipped(s.contained_refs)
                if s.total_bytes <= cfg.max_direct_call_object_size:
                    out.append(("value", s.to_bytes()))
                else:
                    ref = self.put(a)
                    # Pin: the promoted ref's only Python instance dies right
                    # here, so without the task-dep pin the object would be
                    # freed before the executor fetches it.
                    self._pin_for_submission(ref, task_id)
                    out.append(("ref", ref.id, ref.owner_address))
        return out

    def _pin_for_submission(self, ref: ObjectRef,
                            task_id: Optional[TaskID]) -> None:
        """Pin an owned arg for a task's lifetime. Pins are RECORDED per
        task so the unpin decrements exactly what was pinned: an arg whose
        entry was already freed at pin time must not be decremented at
        report time (it may have been recreated by recursive recovery in
        between, and an unmatched decrement would drive the count negative
        and let a later task's dep be freed out from under it). task_id
        None (actor-creation args) pins for the actor's lifetime."""
        if ref.owner_address != self.address:
            return
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is not None:
                st.submitted_task_deps += 1
                st.shipped = True  # the executor materializes a borrow
                if task_id is not None:
                    self._task_pins.setdefault(task_id, []).append(ref.id)

    def _mark_shipped(self, refs) -> None:
        """Mark owned objects whose refs were serialized into an outgoing
        payload: their frees get the borrow-in-flight grace period."""
        for r in refs or ():
            if r.owner_address == self.address:
                with self._obj_lock:
                    st = self._objects.get(r.id)
                    if st is not None:
                        st.shipped = True

    def _unpin_after_task(self, spec: TaskSpec) -> None:
        """Release exactly the pins _pin_for_submission recorded for this
        task (pop makes a double report idempotent)."""
        with self._obj_lock:
            for oid in self._task_pins.pop(spec.task_id, ()):
                st = self._objects.get(oid)
                if st is not None:
                    st.submitted_task_deps -= 1
                    self._maybe_free(oid, st)

    # ------------------------------------------------------------------ put
    @property
    def _current_task_id(self) -> TaskID:
        return getattr(self._tls, "task_id", self._root_task_id)

    def put(self, value: Any) -> ObjectRef:
        with self._put_lock:
            self._put_counter += 1
            put_index = self._put_counter
        oid = ObjectID.for_put(self._current_task_id, put_index)
        s = serialization.serialize(value)
        cfg = get_config()
        with self._obj_lock:
            st = _ObjectState(local_refs=1)
            self._objects[oid] = st
        if s.total_bytes <= cfg.max_direct_call_object_size:
            blob = s.to_bytes()
            with self._obj_lock:
                st.state = "inline"
                st.inline_blob = blob
                st.size = len(blob)
                self._obj_cv.notify_all()
        else:
            seg = self._put_to_store(oid, s)
            with self._obj_lock:
                st.state = "plasma"
                st.location = self.raylet_address
                st.size = s.total_bytes
                st.segment = seg
                self._obj_cv.notify_all()
        # Refs nested in the stored value: shipping them into the store means
        # borrows can materialize later from any reader. Owned inner objects
        # additionally get a CONTAINER PIN — they stay alive as long as the
        # enclosing object does, because a reader may deserialize the payload
        # (and only then register its borrow) arbitrarily late. The reference
        # tracks this as nested-ref containment in its borrow tables
        # (reference_count.h:834); a grace window alone cannot cover it.
        self._mark_shipped(s.contained_refs)
        with self._obj_lock:
            seen = set()
            for r in s.contained_refs or ():
                if (r.owner_address == self.address and r.id != oid
                        and r.id not in seen and r.id in self._objects):
                    seen.add(r.id)
                    self._objects[r.id].container_pinned += 1
                    st.contained_pins.append(r.id)
        self._notify_info_waiters(oid)
        ref = ObjectRef(oid, owner_address=self.address)
        ref._counted = True
        return ref

    # ------------------------------------------------------------- promises
    def create_promise(self) -> ObjectRef:
        """An owned object with no producing task: the creator resolves it
        later via fulfill_promise(). Every consumer path (get/wait/
        add_done_callback/try_get_local) works unchanged. Serve's router
        returns one per routed request so a mid-request replica failover
        can re-point the work without changing the caller-visible ref."""
        with self._put_lock:
            self._put_counter += 1
            put_index = self._put_counter
        oid = ObjectID.for_put(self._current_task_id, put_index)
        with self._obj_lock:
            self._objects[oid] = _ObjectState(local_refs=1)
        ref = ObjectRef(oid, owner_address=self.address)
        ref._counted = True
        return ref

    def fulfill_promise(self, ref: ObjectRef, value: Any = None,
                        error: Optional[BaseException] = None) -> bool:
        """Resolve a pending promise with a value or an exception. First
        resolution wins; returns False if the promise was already terminal
        (a lost race with the deadline reaper is normal, not an error)."""
        if error is not None:
            return self.fulfill_promise_blob(
                ref, serialization.dumps(error), is_error=True)
        s = serialization.serialize(value)
        self._mark_shipped(s.contained_refs)
        ok = self.fulfill_promise_blob(ref, s.to_bytes(), is_error=False)
        if ok:
            # same nested-ref containment as put(): owned refs inside the
            # stored value get a container pin for the promise's lifetime —
            # a reader may deserialize (and only then register its borrow)
            # arbitrarily late, which the shipped grace window alone cannot
            # cover (reference reference_count.h:834)
            with self._obj_lock:
                st = self._objects.get(ref.id)
                if st is not None:
                    seen = set()
                    for r in s.contained_refs or ():
                        if (r.owner_address == self.address and r.id != ref.id
                                and r.id not in seen
                                and r.id in self._objects):
                            seen.add(r.id)
                            self._objects[r.id].container_pinned += 1
                            st.contained_pins.append(r.id)
        return ok

    def fulfill_promise_blob(self, ref: ObjectRef, blob: bytes,
                             is_error: bool) -> bool:
        """Resolve a promise with an already-serialized payload — the
        zero-reserialization path for relaying another owned object's
        terminal inline/error blob (serve router success/error relay)."""
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is None or st.state != "pending":
                return False
            st.state = "error" if is_error else "inline"
            st.inline_blob = blob
            st.size = len(blob)
            self._obj_cv.notify_all()
        self._notify_info_waiters(ref.id)
        return True

    def peek_local(self, ref: ObjectRef):
        """(state, inline_blob) snapshot of an owned object's record —
        (None, None) if unknown. Non-blocking; lets completion callbacks
        classify a terminal object without a get()."""
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is None:
                return None, None
            return st.state, st.inline_blob

    def _put_to_store(self, oid: ObjectID,
                      s: SerializedObject) -> Optional[Tuple[str, int]]:
        """Write a serialized object into the node store and seal it.

        One control round-trip total: obj_create is the only CALL (the
        allocation decision must come back); the seal rides the same
        ordered connection as a fire-and-forget notify. The write itself
        picks the cheapest memory path: a recycled segment's pages are
        already faulted, so memcpy through a mapping runs at memory
        bandwidth; a fresh file takes os.writev, which populates tmpfs
        pages directly instead of zero-faulting a fresh mapping first
        (the buffer-protocol put fast path — numpy/JAX host array buffers
        go straight from the array to the segment, no flatten).

        Returns (segment_name, attach_size), or None if the object
        already existed.

        Store-full backpressure: a typed `full` refusal retries with
        backoff for at most `put_full_timeout_s` — eviction, spilling and
        reader unpins happen on the raylet in the meantime — then raises
        ObjectStoreFullError (immediately when the store marks the refusal
        `fatal`: the object can never fit)."""
        size = s.framed_size
        cfg = get_config()
        deadline = time.monotonic() + cfg.put_full_timeout_s
        attempt = 0
        while True:
            # job_id rides along so the raylet can attribute the primary
            # copy: a dead job's reap deletes its objects by this stamp
            r = self.raylet.call("obj_create",
                                 {"object_id": oid, "size": size,
                                  "job_id": self.job_id.binary()})
            if r.get("ok"):
                break
            if not r.get("full"):
                return None  # already exists
            remaining = deadline - time.monotonic()
            if r.get("fatal") or remaining <= 0:
                raise ObjectStoreFullError(
                    r.get("error")
                    or f"object store full putting {oid} ({size} bytes)")
            attempt += 1
            time.sleep(min(0.05 * attempt, 0.5, max(remaining, 0.01)))
        name = r["name"]
        if name.startswith("@"):
            buf = attach_object(name, size)  # arena slot: write in place
            try:
                s.write_into(buf.view)
            finally:
                buf.close()
        else:
            dst = self._writer_map_view(name, size)
            if dst is not None:
                # hottest path: a recycled segment THIS process has written
                # before — page tables already populated, pure memcpy
                try:
                    s.write_into(dst)
                finally:
                    dst.release()
            else:
                # writev, never a fresh writer-side mapping: a fresh
                # mapping zero-faults every page before the copy, and even
                # on a recycled (hot) segment populating the page table
                # costs ~5x the fd write path. Cache a mapping for the
                # segment's NEXT reuse by this process.
                from ray_tpu.core.object_store import _SHM_DIR

                fd = os.open(os.path.join(_SHM_DIR, name), os.O_WRONLY)
                try:
                    s.write_to_fd(fd)
                finally:
                    os.close(fd)
                self._writer_map_add(name)
        self.raylet.notify("obj_seal", {"object_id": oid})
        seg = None
        if not name.startswith("@"):
            seg = (name, size)
            self._seg_cache_put(oid, name, size)
        return seg

    # ------------------------------------------------------------------ get
    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(r, deadline) for r in refs]

    def try_get_local(self, ref: ObjectRef):
        """(value, True) when the owned object is terminal AND resolvable
        without blocking (inline or error blob in the local table) — the
        post-completion fast path for event-loop callers (serve's HTTP
        edge). (None, False) means call get() on a thread that may block."""
        if ref.owner_address not in ("", self.address):
            return None, False
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is None or st.state != "inline":
                # plasma needs a fetch; errors go through get() so exception
                # rewrapping semantics stay in one place
                return None, False
            blob = st.inline_blob
        return serialization.loads(blob), True

    def get_async(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self._get_one(ref, None))
            except Exception as e:
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        recoveries = 0
        failed_sources: set = set()
        while True:
            info = self._resolve(ref, deadline)
            kind = info["kind"]
            if kind == "inline":
                return serialization.loads(info["data"])
            if kind == "plasma":
                source = info.get("raylet")
                if source in failed_sources:
                    # Re-resolved to a location that already failed: the copy
                    # really is gone. Lineage recovery (reference
                    # object_recovery_manager.h:96): recompute by re-executing
                    # the creating task, then resolve the fresh location.
                    if (recoveries < get_config().lineage_reconstruction_max_retries
                            and self._recover_object(ref)):
                        recoveries += 1
                        failed_sources.clear()
                        continue
                    raise ObjectLostError(
                        f"object {ref.id} lost from {source} and could not "
                        f"be reconstructed")
                try:
                    value = self._fetch_plasma(ref, info, deadline)
                    self._note_pulled_copy(ref)
                    return value
                except ObjectLostError:
                    # First failure of this source: tell the owner so other
                    # resolvers stop being pointed at the stale copy, then
                    # re-resolve before spending a reconstruction — another
                    # location (or a concurrent getter's recovery) may serve.
                    self._note_location_failed(ref, source)
                    failed_sources.add(source)
                    continue
            if kind == "error":
                err = serialization.loads(info["data"])
                if isinstance(err, TaskError) and err.cause is not None:
                    # Re-raise the user's original exception type with the
                    # remote traceback attached (cf. reference
                    # as_instanceof_cause).
                    raise err.cause from err
                raise err
            raise ObjectLostError(f"object {ref.id} in unexpected state {kind}")

    def _resolve(self, ref: ObjectRef, deadline: Optional[float]) -> dict:
        """Find where the object's bytes are (blocking until produced)."""
        if ref.owner_address in ("", self.address):
            with self._obj_cv:
                st = self._objects.get(ref.id)
            if st is None:
                # Data already freed, but if the lineage survives we can
                # recompute (needed when a reconstructed task's own args were
                # freed after its first run). Outside the cv: _try_reconstruct
                # does network sends and must not run under _obj_lock.
                if ref.id in self._lineage and self._try_reconstruct(ref.id):
                    with self._obj_cv:
                        st = self._objects.get(ref.id)
            if st is None:
                raise ObjectLostError(
                    f"object {ref.id} is not owned by this process and has no owner address")
            with self._obj_cv:
                while st.state == "pending":
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise GetTimeoutError(f"get() timed out waiting for {ref.id}")
                    self._obj_cv.wait(timeout=min(remaining, 1.0) if remaining else 1.0)
                if st.state == "inline":
                    return {"kind": "inline", "data": st.inline_blob}
                if st.state == "error":
                    return {"kind": "error", "data": st.inline_blob}
                info = {"kind": "plasma", "raylet": st.location,
                        "size": st.size}
                if st.segment is not None:
                    info["segment"] = st.segment
                    info["segment_at"] = st.location
                return info
        # borrowed: ask the owner
        timeout = None if deadline is None else max(deadline - time.monotonic(), 0.01)
        try:
            info = self.peer(ref.owner_address).call(
                "get_object_info", {"object_id": ref.id, "wait": True},
                timeout=timeout)
        except (rpc.RpcDisconnected, OSError):
            # conn severed mid-call OR connect refused outright — either
            # way the ownership record is gone with the process (cross-job
            # get of a reaped job's object lands here)
            raise OwnerDiedError(
                f"owner {ref.owner_address} of object {ref.id} died") from None
        except TimeoutError:
            raise GetTimeoutError(f"get() timed out waiting for {ref.id}") from None
        if info is None:
            raise ObjectLostError(f"owner has no record of object {ref.id}")
        return info

    def _fetch_plasma(self, ref: ObjectRef, info: dict, deadline: Optional[float]) -> Any:
        """Materialize a plasma object's value.

        Same-node fast path (zero-copy): when the segment name is known —
        from the worker-side location cache or the owner's reply — attach
        it and deserialize IN PLACE, pipelined with an authoritative
        obj_pin round-trip; the returned value's large buffers are
        read-only views into shared memory, pinned on the raylet until the
        reader's last view is GC'd. Fallback: pull_object (which pins
        before replying), then attach; only arena-resident objects (and
        zero-copy-disabled configs) pay a copy out of the segment."""
        source = info["raylet"]
        zc = get_config().object_zero_copy_enabled
        if zc:
            cached = self._seg_cache_get(ref.id)
            if cached is None and info.get("segment") is not None \
                    and info.get("segment_at") == self.raylet_address:
                cached = tuple(info["segment"])
            if cached is not None and not cached[0].startswith("@"):
                value, ok = self._pinned_load(ref.id, cached[0], cached[1])
                if ok:
                    return value
        last_err: object = None
        for _ in range(3):
            timeout = None if deadline is None else max(deadline - time.monotonic(), 0.01)
            try:
                # ALWAYS pin the pull — even on the copy path. The store's
                # segment-reuse pool means an unpinned segment deleted
                # mid-copy could be recycled and overwritten under the
                # reader (pre-pool, the open mapping kept the dead inode's
                # bytes stable); the pin blocks the delete until the copy
                # (or the zero-copy reader's last view) releases it.
                loc = self.raylet.call(
                    "pull_object",
                    {"object_id": ref.id, "source": source, "pin": True},
                    timeout=timeout)
            except TimeoutError:
                raise GetTimeoutError(
                    f"get() timed out pulling {ref.id}") from None
            except Exception as e:
                # Source raylet dead or pull failed — surface as lost so
                # _get_one can attempt lineage recovery.
                raise ObjectLostError(
                    f"object {ref.id} could not be pulled from {source}: {e}"
                ) from None
            name, size = loc[0], loc[1]
            # a third "copy_only" element means the raylet granted a
            # TRANSIENT pin (indefinite reader pins are at the
            # max_pinned_fraction cap): copy out inside the bounded pin
            # window instead of arming a finalizer-held zero-copy view
            copy_only = len(loc) > 2 and loc[2] == "copy_only"
            if zc and not copy_only and not name.startswith("@"):
                value, ok = self._pinned_load(ref.id, name, size,
                                              pre_pinned=True)
                if ok:
                    return value
                last_err = "pinned segment vanished"
                continue
            # copy path: arena-resident objects (their slots recycle on
            # free, so views may only alias shm UNDER a pin — the pull
            # reply's pin covers exactly this copy window), pin-cap
            # copy_only grants, or zc disabled
            try:
                buf = attach_object(name, size)
            except FileNotFoundError as e:
                # Segment was spilled/evicted between lookup and attach; the
                # next pull_object restores it from spill.
                self._unpin_notify(ref.id)
                last_err = e
                continue
            try:
                data = bytes(buf.view)  # one copy out of shm: values own their memory
            finally:
                buf.close()
                self._unpin_notify(ref.id)
            return serialization.loads(data)
        raise ObjectLostError(f"object {ref.id} vanished during fetch: {last_err}")

    # ------------------------------------------------ zero-copy pin plumbing
    def _pinned_load(self, oid: ObjectID, name: str, size: int,
                     pre_pinned: bool = False):
        """Attach a local segment and deserialize in place, returning
        (value, ok). The attach + deserialize run OPTIMISTICALLY, pipelined
        with the obj_pin round-trip; the value is only trusted once the pin
        reply confirms the exact segment we attached (which is what makes
        the store's segment recycling safe — a recycled inode can never
        confirm). With `pre_pinned` the pin is already held (pull_object
        reply / a mismatch retry), so no confirmation round-trip is needed.
        On ok=True an unpin finalizer is armed on the mapping: it fires
        when the reader's LAST view over the segment is GC'd."""
        fut = None
        if not pre_pinned:
            try:
                fut = self.raylet.call_future("obj_pin", {"object_id": oid})
            except Exception:
                return None, False
        attached = None
        value = None
        err = None
        try:
            attached = attach_object(name, size, readonly=True)
            value = serialization.loads_view(attached.view)
        except Exception as e:
            # garbage from a recycled segment can fail to unpickle; a
            # vanished one fails to open — either way the pin reply decides
            err = e
        if fut is not None:
            try:
                loc = fut.result(
                    timeout=get_config().rpc_connect_timeout_s)
            except Exception:
                # reply lost/timed out — but the pin REQUEST may still be
                # in flight and land later. The compensating unpin rides
                # the same ordered connection, so it is processed after
                # the pin if it landed (and is a tracked-map no-op if it
                # didn't) — without this, a slow raylet leaks a pin that
                # blocks reclaim for the connection's lifetime.
                self._unpin_notify(oid)
                self._seg_cache_drop(oid)
                return None, False
            if loc is None:
                # pin missed: the object is gone here (deleted, or spilled
                # and not restorable) — nothing to release, fall back
                self._seg_cache_drop(oid)
                return None, False
            if tuple(loc) != (name, size):
                self._seg_cache_drop(oid)
                if loc[0].startswith("@"):
                    # the object now lives in the ARENA (deleted + re-put
                    # by lineage re-execution): arena slots are not
                    # zero-copy eligible — release the pin and let the
                    # pull path's pinned copy handle it
                    self._unpin_notify(oid)
                    return None, False
                # pinned, but the segment moved (spill+restore): retry on
                # the authoritative location with the pin already held
                return self._pinned_load(oid, loc[0], loc[1],
                                         pre_pinned=True)
        if err is not None:
            # the pin IS held (confirmed or pre-held) but the local attach/
            # decode failed: release it and fall back to the pull path
            self._unpin_notify(oid)
            self._seg_cache_drop(oid)
            return None, False
        self._seg_cache_put(oid, name, size)
        self._arm_unpin_finalizer(oid, attached)
        return value, True

    def _arm_unpin_finalizer(self, oid: ObjectID, attached) -> None:
        """Tie the raylet-side pin to the mapping's lifetime: every view
        handed out by loads_view keeps the mmap alive (buffer-protocol
        exporter chain), so the finalizer fires exactly when the reader's
        last view dies — including 'immediately', for values that kept no
        buffer (pure-payload pickles)."""
        weakref.finalize(attached._shm._mmap, _send_unpin,
                         weakref.ref(self), oid)

    def _unpin_notify(self, oid: ObjectID) -> None:
        try:
            self.raylet.notify("obj_unpin", {"object_id": oid})
        except Exception:
            logger.debug("obj_unpin for %s lost", oid, exc_info=True)

    def _seg_cache_put(self, oid: ObjectID, name: str, size: int) -> None:
        with self._seg_cache_lock:
            self._seg_cache[oid] = (name, size)
            self._seg_cache.move_to_end(oid)
            cap = get_config().object_location_cache_entries
            while len(self._seg_cache) > cap:
                self._seg_cache.popitem(last=False)

    def _seg_cache_get(self, oid: ObjectID) -> Optional[Tuple[str, int]]:
        with self._seg_cache_lock:
            e = self._seg_cache.get(oid)
            if e is not None:
                self._seg_cache.move_to_end(oid)
            return e

    def _seg_cache_drop(self, oid: ObjectID) -> None:
        with self._seg_cache_lock:
            self._seg_cache.pop(oid, None)

    _WRITE_MAPS_MAX = 16

    def _writer_map_view(self, name: str, size: int):
        """Writable view over the cached mapping of a segment obj_create
        just granted us (create grants exclusive write ownership until
        seal, so writing through a retained mapping is safe — stale
        entries for names the store has moved on from are never handed
        back by create). The view is exported UNDER the lock: a racing
        LRU eviction's close() then raises BufferError and is skipped,
        so a concurrent put can never be handed a closed mapping."""
        with self._write_maps_lock:
            m = self._write_maps.get(name)
            if m is None or len(m) < size:
                return None
            self._write_maps.move_to_end(name)
            return memoryview(m)[:size]

    def _writer_map_add(self, name: str) -> None:
        import mmap as _mmap

        from ray_tpu.core.object_store import _SHM_DIR

        path = os.path.join(_SHM_DIR, name)
        try:
            fd = os.open(path, os.O_RDWR)
            try:
                m = _mmap.mmap(fd, os.fstat(fd).st_size)
            finally:
                os.close(fd)
        except (OSError, ValueError):
            return
        evicted = []
        with self._write_maps_lock:
            old = self._write_maps.pop(name, None)
            if old is not None:
                self._write_maps_bytes -= len(old)
                evicted.append(old)
            self._write_maps[name] = m
            self._write_maps_bytes += len(m)
            cap_bytes = get_config().object_segment_pool_bytes
            while self._write_maps and (
                    len(self._write_maps) > self._WRITE_MAPS_MAX
                    or self._write_maps_bytes > cap_bytes):
                _, old = self._write_maps.popitem(last=False)
                self._write_maps_bytes -= len(old)
                evicted.append(old)
        for old in evicted:
            try:
                old.close()
            except (BufferError, ValueError):
                pass  # transient exported view; GC unmaps

    def _note_pulled_copy(self, ref: ObjectRef) -> None:
        """A successful pull materialized a copy on OUR raylet: register it
        with the owner so later readers spread across holders (once per
        object — repeat gets of a hot ref must not spam the owner)."""
        with self._registered_copies_lock:
            if ref.id in self._registered_copies:
                self._registered_copies.move_to_end(ref.id)
                return
            self._registered_copies[ref.id] = True
            # bounded LRU: evict the COLDEST entry instead of clearing the
            # whole set (a clear made every hot ref re-notify its owner at
            # once — exactly wrong at the 10k-objects-per-get envelope)
            if len(self._registered_copies) > 100_000:
                self._registered_copies.popitem(last=False)
        try:
            if ref.owner_address in ("", self.address):
                with self._obj_lock:
                    st = self._objects.get(ref.id)
                    if (st is not None and st.state == "plasma"
                            and self.raylet_address != st.location
                            and self.raylet_address not in st.extra_locations):
                        st.extra_locations.append(self.raylet_address)
            else:
                self.peer(ref.owner_address).notify(
                    "add_object_location",
                    {"object_id": ref.id, "raylet": self.raylet_address})
        except (OSError, RuntimeError, TimeoutError):
            logger.debug("copy registration for %s failed", ref.id,
                         exc_info=True)

    def _note_location_failed(self, ref: ObjectRef, source: Optional[str]) -> None:
        if not source:
            return
        try:
            if ref.owner_address in ("", self.address):
                self._drop_location(ref.id, source)
            else:
                self.peer(ref.owner_address).notify(
                    "object_location_failed",
                    {"object_id": ref.id, "raylet": source})
        except (OSError, RuntimeError, TimeoutError):
            logger.debug("location-failed report for %s lost", ref.id,
                         exc_info=True)

    # ------------------------------------------------------ lineage recovery
    def _recover_object(self, ref: ObjectRef) -> bool:
        """Arrange for a lost object to be recomputed. Returns True if a
        reconstruction was started (or is already in flight) and the caller
        should re-resolve; False if the object is unrecoverable."""
        if ref.owner_address in ("", self.address):
            return self._try_reconstruct(ref.id)
        try:
            return bool(self.peer(ref.owner_address).call(
                "reconstruct_object", {"object_id": ref.id}, timeout=30))
        except (OSError, RuntimeError, TimeoutError):  # owner gone: unrecoverable via that owner
            return False

    def rpc_reconstruct_object(self, conn, req_id, payload):
        """A borrower's pull failed: recompute the object we own
        (reference ObjectRecoveryManager::ReconstructObject)."""
        return self._try_reconstruct(payload["object_id"])

    def _try_reconstruct(self, oid: ObjectID) -> bool:
        """Owner-side: re-execute the creating task of a lost object
        (lineage re-execution, reference object_recovery_manager.h:96).
        Bounded per creating task by lineage_reconstruction_max_retries.
        Callers must NOT hold _obj_lock: the trailing notifies do network I/O.
        """
        cfg = get_config()
        # Owner-side liveness probe first (reference ObjectRecoveryManager
        # pins/locates before reconstructing): if ANY known location still
        # holds the object, repair the directory instead of re-executing —
        # a reader's failed pull of one stale copy must not re-run tasks.
        with self._obj_lock:
            st0 = self._objects.get(oid)
            locs = ([st0.location] + list(st0.extra_locations)
                    if st0 is not None and st0.state == "plasma" else [])
        live = None
        for loc in locs:
            if not loc:
                continue
            try:
                if loc == self.raylet_address:
                    found = self.raylet.call("obj_lookup", {"object_id": oid},
                                             timeout=3)
                else:
                    # short-lived, short-timeout probe: peer() would retry
                    # connecting to a dead raylet for rpc_connect_timeout_s
                    # (30s) — far too long for a liveness check, and this
                    # runs on the RPC handler path for borrower-triggered
                    # reconstructions
                    probe = rpc.RpcClient(loc, connect_timeout=2)
                    try:
                        found = probe.call("obj_lookup", {"object_id": oid},
                                           timeout=3)
                    finally:
                        probe.close()
                if found is not None:
                    live = loc
                    break
            except Exception:
                continue
        if live is not None:
            with self._obj_lock:
                st0 = self._objects.get(oid)
                if st0 is not None and st0.state == "plasma":
                    if live != st0.location:
                        st0.segment = None  # name was the OLD primary's
                    st0.location = live
                    st0.extra_locations = []  # dead copies re-register on pull
            return True
        with self._obj_lock:
            spec = self._lineage.get(oid)
            if spec is None:
                return False
            # The in-flight check and the pending-table insertion are one
            # critical section: without it two concurrent getters both see
            # not-in-flight and double-submit (double execution + one
            # balancing unpin for two pins).
            with self._pending_lock:
                if spec.task_id in self._pending_tasks:
                    submit = False
                else:
                    attempts = self._lineage_attempts.get(spec.task_id, 0)
                    if attempts >= cfg.lineage_reconstruction_max_retries:
                        return False
                    self._lineage_attempts[spec.task_id] = attempts + 1
                    self._pending_tasks[spec.task_id] = [spec, 0]
                    submit = True
            # All returns of the task are recomputed together (incl. any
            # dynamic generator items — same deterministic ids); reset their
            # states so concurrent getters block until the re-run reports.
            for roid in (spec.return_object_ids()
                         + list(self._task_dynamic_ids.get(spec.task_id, ()))):
                st = self._objects.get(roid)
                if st is None:
                    st = _ObjectState()
                    self._objects[roid] = st
                if st.state == "plasma" or submit:
                    st.state = "pending"
            if submit:
                # Re-pin argument objects we own for the duration of the
                # re-run (balanced by _unpin_after_task on result report);
                # pinned before the release so the report can't unpin first.
                for a in spec.args:
                    if a[0] == "ref" and a[2] == self.address:
                        self._pin_for_submission(
                            ObjectRef(a[1], owner_address=a[2]), spec.task_id)
        if submit:
            logger.info("reconstructing %s by re-executing task %s",
                        oid, spec.method_name)
            self._emit_task_event(spec, "SUBMITTED")
            self.raylet.notify("submit_task", {"spec": spec})
        return True

    # ------------------------------------------------------------------ wait
    def wait(self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float],
             fetch_local: bool = True):
        if len({r.id for r in refs}) != len(refs):
            # the reference rejects duplicates too; silently collapsing them
            # would make len(ready)+len(pending) != len(refs)
            raise ValueError("wait() got duplicate object refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        if all(r.owner_address in ("", self.address) for r in refs):
            return self._wait_owned(refs, num_returns, deadline)
        # Borrowed refs ride the owners' DEFERRED-REPLY path: one
        # get_object_info(wait=True) future per ref, resolved by the owner
        # when the object turns terminal — no per-tick RPC storm and no
        # get_check_interval_s latency floor (the old design polled every
        # owner for every ref each interval; reference WaitManager is
        # event-driven end to end). An owner's error/disconnect counts the
        # ref ready: the subsequent get() surfaces the real failure.
        # Futures are CACHED per (owner, object): the canonical poll loop —
        # wait(timeout=...) in a while — reuses one outstanding deferred
        # call instead of parking a fresh owner-side waiter per tick.
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as futures_wait

        owned_ids = {r.id for r in refs
                     if r.owner_address in ("", self.address)}
        owned = [r for r in refs if r.id in owned_ids]
        futures: Dict[ObjectRef, Any] = {}
        ready: List[ObjectRef] = []
        ready_ids = set()
        for r in refs:
            if r.id in owned_ids:
                continue
            f = self._borrowed_wait_future(r)
            if f is None:
                ready.append(r)  # owner unreachable: ready-with-error
                ready_ids.add(r.id)
            else:
                futures[r] = f
        while True:
            for r in [r for r, f in futures.items() if f.done()]:
                self._drop_wait_future(r, futures.pop(r))
                ready.append(r)
                ready_ids.add(r.id)
            owned_pending = []
            for r in owned:
                if r.id in ready_ids:
                    continue
                with self._obj_lock:
                    st = self._objects.get(r.id)
                    terminal = st is not None and st.state != "pending"
                if terminal:
                    ready.append(r)
                    ready_ids.add(r.id)
                else:
                    owned_pending.append(r)
            pending = owned_pending + list(futures)
            if len(ready) >= num_returns or not pending:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            # owned refs have no future to park on: bound the sleep so
            # their cv-side transitions are observed promptly
            slice_s = min(0.2, remaining) if remaining is not None else \
                (0.2 if owned_pending else None)
            if futures:
                futures_wait(list(futures.values()), timeout=slice_s,
                             return_when=FIRST_COMPLETED)
            else:
                with self._obj_cv:
                    self._obj_cv.wait(timeout=slice_s or 5.0)
        # preserve input order within each bucket for determinism
        order = {id(r): i for i, r in enumerate(refs)}
        ready.sort(key=lambda r: order[id(r)])
        pending.sort(key=lambda r: order[id(r)])
        return ready[:num_returns], pending + ready[num_returns:]

    def _borrowed_wait_future(self, ref: ObjectRef):
        """One OUTSTANDING get_object_info(wait=True) future per borrowed
        object: repeated wait() calls share it, so a poll loop parks exactly
        one owner-side waiter per object instead of one per tick."""
        key = (ref.owner_address, ref.id)
        with self._wait_futures_lock:
            f = self._wait_futures.get(key)
            if f is not None and not f.done():
                self._wait_futures.move_to_end(key)
                return f
            try:
                f = self.peer(ref.owner_address).call_future(
                    "get_object_info", {"object_id": ref.id, "wait": True})
            except Exception:
                self._wait_futures.pop(key, None)
                return None
            self._wait_futures[key] = f
            # bounded LRU: a stream of abandoned timed-out waits over
            # distinct refs must not grow this forever (evicting a live
            # entry only means a later wait() re-issues the call)
            while len(self._wait_futures) > 4096:
                self._wait_futures.popitem(last=False)
            return f

    def _drop_wait_future(self, ref: ObjectRef, fut) -> None:
        with self._wait_futures_lock:
            if self._wait_futures.get((ref.owner_address, ref.id)) is fut:
                self._wait_futures.pop((ref.owner_address, ref.id), None)

    def _wait_owned(self, refs: List[ObjectRef], num_returns: int,
                    deadline: Optional[float]):
        """Event-driven wait for refs we own: sleeps on the object condition
        variable (notified at every state transition) instead of polling —
        no get_check_interval_s latency floor (reference WaitManager is
        likewise event-driven)."""
        with self._obj_cv:
            while True:
                ready = []
                pending = []
                for r in refs:
                    st = self._objects.get(r.id)
                    if st is not None and st.state != "pending":
                        ready.append(r)
                    else:
                        pending.append(r)
                if len(ready) >= num_returns or not pending:
                    break
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._obj_cv.wait(timeout=min(remaining, 5.0) if remaining else 5.0)
        return ready[:num_returns], pending + ready[num_returns:]

    # -------------------------------------------------- owner-side RPC surface
    def rpc_get_object_info(self, conn, req_id, payload):
        oid: ObjectID = payload["object_id"]
        wait = payload.get("wait", False)
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is None:
                return None
            if st.state == "pending":
                if not wait:
                    return {"kind": "pending"}
                st.waiters.append((conn, req_id))
                return rpc.RpcServer.DEFERRED
            return self._info_payload(st)

    def _info_payload(self, st: _ObjectState) -> dict:
        if st.state == "inline":
            return {"kind": "inline", "data": st.inline_blob}
        if st.state == "error":
            return {"kind": "error", "data": st.inline_blob}
        # Location spreading (reference OwnershipBasedObjectDirectory with
        # multiple locations): readers that pulled a copy register it, and
        # later readers are pointed at a random holder — a 1 GiB broadcast
        # fans out across copies instead of hammering the primary. The
        # primary's segment name rides along so a reader CO-LOCATED with it
        # attaches directly, skipping the pull_object round-trip.
        locs = [st.location] + st.extra_locations
        info = {"kind": "plasma", "raylet": random.choice(locs),
                "size": st.size}
        if st.segment is not None:
            info["segment"] = st.segment
            info["segment_at"] = st.location
        return info

    def rpc_add_object_location(self, conn, req_id, payload):
        """A reader materialized a copy of our object on its raylet."""
        with self._obj_lock:
            st = self._objects.get(payload["object_id"])
            loc = payload["raylet"]
            if (st is not None and st.state == "plasma"
                    and loc != st.location and loc not in st.extra_locations):
                st.extra_locations.append(loc)
        return True

    def rpc_object_location_failed(self, conn, req_id, payload):
        """A reader's pull from `raylet` failed: prune the stale copy
        (evicted or node died) so resolvers stop being pointed at it."""
        self._drop_location(payload["object_id"], payload["raylet"])
        return True

    def _drop_location(self, oid: ObjectID, loc: str) -> None:
        """Prune a stale PULLED copy. The pinned primary is never dropped on
        a reader's report alone (a transient pull failure would orphan the
        pinned plasma copy); primary repair happens in _try_reconstruct's
        owner-side liveness probe."""
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is not None and loc in st.extra_locations:
                st.extra_locations.remove(loc)

    def add_done_callback(self, ref: ObjectRef, cb: Callable[[], None]) -> None:
        """Invoke `cb` (cheap, non-blocking!) when the owned object reaches a
        terminal state — the thread-free alternative to polling/`get_async`
        for completion accounting (e.g. Serve's in-flight router counts).
        Fires immediately if already terminal; runs on the RPC reader thread
        otherwise."""
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is not None and st.state == "pending":
                st.callbacks.append(cb)
                return
        try:
            cb()
        except Exception:
            logger.exception("done callback failed")

    def _notify_info_waiters(self, oid: ObjectID) -> None:
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is None or st.state == "pending":
                return
            waiters, st.waiters = st.waiters, []
            callbacks, st.callbacks = st.callbacks, []
            payload = self._info_payload(st)
        for conn, req_id in waiters:
            try:
                conn.reply(req_id, payload)
            except OSError as e:
                logger.debug("waiter connection dropped before reply: %s", e)
        for cb in callbacks:
            try:
                cb()
            except Exception:
                logger.exception("done callback failed")

    def rpc_report_task_result(self, conn, req_id, payload):
        """Executor pushed results for task(s) we own. Accepts both the
        legacy single-task payload and the ResultBuffer's multi-task batch
        (`{"batch": [(task_id, results), ...]}`, applied in completion
        order); object-state wakeups coalesce into ONE `_obj_cv.notify_all()`
        per call instead of one per result entry. Actor replies carry the
        reporting instance's incarnation: a LATE reply from a superseded
        instance (partition heal) is rejected here rather than applied."""
        batch = payload.get("batch")
        if batch is None:
            batch = [(payload["task_id"], payload["results"])]
        reporter_inc = payload.get("actor_incarnation")
        for task_id, results in batch:
            if reporter_inc is not None \
                    and self._reject_stale_reply(task_id, reporter_inc):
                continue
            try:
                self._handle_task_result(task_id, results)
            except Exception:
                # tasks were isolated per-RPC before batching; one bad
                # entry must not strand the other tasks riding the batch
                logger.exception("failed to apply results of task %s", task_id)
        with self._obj_cv:
            self._obj_cv.notify_all()
        return True

    def _reject_stale_reply(self, task_id: TaskID, reporter_inc: int) -> bool:
        """True when this reply comes from an actor incarnation OLDER than
        the one the call was pinned to — it must not resolve the task's
        objects (the pinned incarnation's own reply, or a failover path,
        owns that)."""
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
            if pend is None:
                return False  # unknown task: normal idempotent-drop path
            spec = pend[0]
            pinned = getattr(spec, "actor_incarnation", None)
            if spec.task_type != TaskType.ACTOR_TASK or pinned is None \
                    or reporter_inc >= pinned:
                return False
        self.stale_reply_rejections += 1
        try:
            from ray_tpu.util.metrics import get_or_create

            get_or_create(
                "counter", "ray_tpu_stale_incarnation_rejections_total",
                "messages rejected for carrying a superseded node/actor "
                "incarnation", tag_keys=("site",)).inc(
                    tags={"site": "task_reply"})
        except Exception:
            pass
        logger.warning(
            "rejected late reply for task %s from superseded actor "
            "incarnation %d (call pinned to %d)", task_id, reporter_inc,
            pinned)
        return True

    def _handle_task_result(self, task_id: TaskID, results) -> None:
        """Apply one task's reported results. Does NOT notify _obj_cv — the
        batch handler wakes waiters once per batch."""
        # Application-level retry (cf. reference retry_exceptions): resubmit
        # instead of recording the error while budget remains. The retry
        # decision (read budget, decrement, or pop) is atomic so a concurrent
        # worker-death notification can't double-spend the budget.
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
            cancelled = task_id in self._cancelled_tasks
            retry = (pend is not None and pend[0].retry_exceptions and pend[1] > 0
                     and not cancelled
                     and any(e[0] == "error" for e in results))
            if retry:
                pend[1] -= 1
                retries_left = pend[1]
            else:
                self._pending_tasks.pop(task_id, None)
                self._fence_resends.pop(task_id, None)
            self._task_locations.pop(task_id, None)
        if cancelled:
            if pend is None:
                # the ref already resolved to TaskCancelledError (dequeue
                # ack, kill report, or failsafe): a straggling report must
                # not overwrite the typed terminal state with a value
                return
            # the task outran the cancel (completed in the race window):
            # the outcome is still deterministic — demote to the typed error
            blob = serialization.dumps(TaskCancelledError(
                f"task {pend[0].method_name} was cancelled"))
            results = [("error", e[1], blob) for e in results]
        if retry:
            delay = get_config().task_retry_delay_ms / 1000.0
            spec = pend[0]
            logger.warning("task %s raised; retrying (%d left)", spec.method_name, retries_left)
            self._resubmit_later(spec, delay)
            return
        for entry in results:
            kind, oid = entry[0], entry[1]
            contained = ()
            with self._obj_lock:
                st = self._objects.get(oid)
                if st is None:
                    st = _ObjectState()
                    self._objects[oid] = st
                if kind == "inline":
                    st.state = "inline"
                    st.inline_blob = entry[2]
                    st.size = len(entry[2])
                    contained = entry[3] if len(entry) > 3 else ()
                elif kind == "plasma":
                    st.state = "plasma"
                    st.location = entry[2]
                    st.extra_locations = []  # stale copies died with the old run
                    st.size = entry[3]
                    contained = entry[4] if len(entry) > 4 else ()
                    st.segment = entry[5] if len(entry) > 5 else None
                elif kind == "error":
                    st.state = "error"
                    st.inline_blob = entry[2]
            if contained:
                self._adopt_contained_refs(oid, contained)
            self._notify_info_waiters(oid)
            # The last ref may have died while the task was still pending
            # (_maybe_free's pending guard kept the entry); now that the
            # state is terminal, free if fully unreferenced.
            with self._obj_lock:
                st = self._objects.get(oid)
                if st is not None:
                    self._maybe_free(oid, st)
        self._finish_dynamic(task_id, results)
        if pend is not None:
            self._unpin_after_task(pend[0])

    # -------------------------------------------------- dynamic returns
    def rpc_report_dynamic_return(self, conn, req_id, payload):
        """Executor push: the NEXT object streamed out of a generator task
        we own (num_returns="dynamic", reference _raylet.pyx:997). The
        object registers like a static return, gains a lineage entry (ids
        are deterministic in (task, index), so re-executing the generator
        recovers any lost item), and its ref is appended for the streaming
        ObjectRefGenerator."""
        task_id: TaskID = payload["task_id"]
        entry = payload["entry"]
        kind, oid = entry[0], entry[1]
        contained = ()
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is None:
                st = _ObjectState()
                self._objects[oid] = st
            if kind == "inline":
                st.state = "inline"
                st.inline_blob = entry[2]
                st.size = len(entry[2])
                contained = entry[3] if len(entry) > 3 else ()
            else:
                st.state = "plasma"
                st.location = entry[2]
                st.extra_locations = []
                st.size = entry[3]
                contained = entry[4] if len(entry) > 4 else ()
                st.segment = entry[5] if len(entry) > 5 else None
            with self._pending_lock:
                pend = self._pending_tasks.get(task_id)
                spec = pend[0] if pend else None
            if spec is not None and spec.task_type == TaskType.NORMAL:
                self._lineage[oid] = spec
                dyn = self._task_dynamic_ids.setdefault(task_id, [])
                if oid not in dyn:
                    dyn.append(oid)
            rec = self._dynamic_returns.get(task_id)
            fire = []
            if (rec is not None and not rec["done"]
                    and oid not in rec.setdefault("seen", set())):
                rec["seen"].add(oid)
                # the record's ref holds one refcount unit until the app's
                # ObjectRefGenerator (or the record itself) drops it
                st.local_refs += 1
                ref = ObjectRef(oid, owner_address=self.address)
                ref._counted = True
                rec["refs"].append(ref)
                fire = self._drain_dynamic_waiters(rec)
            self._obj_cv.notify_all()
        for cb in fire:
            try:
                cb()
            except Exception:
                logger.exception("dynamic-return callback failed")
        if contained:
            self._adopt_contained_refs(oid, contained)
        self._notify_info_waiters(oid)
        return True

    def next_dynamic_return(self, task_id: TaskID, i: int):
        """Streaming accessor for ObjectRefGenerator on the owner: block
        until the i-th dynamic return is reported. Returns (ref, done,
        error); ref None means the stream ended."""
        with self._obj_lock:
            while True:
                rec = self._dynamic_returns.get(task_id)
                if rec is None:
                    return None, True, None
                if i < len(rec["refs"]):
                    return rec["refs"][i], False, None
                if rec["done"]:
                    return None, True, rec["error"]
                if self._shutdown.is_set():
                    return None, True, None
                self._obj_cv.wait(timeout=1.0)

    def object_size(self, ref: ObjectRef):
        """Size in bytes of a TERMINAL owned object (None while pending or
        unknown) — the streaming executor's byte-budget accounting reads
        this without fetching values."""
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is not None and st.state in ("inline", "plasma"):
                return st.size
        return None

    def add_dynamic_return_callback(self, task_id: TaskID, i: int,
                                    cb) -> None:
        """Event-driven streaming: invoke `cb()` (from whichever thread
        reports the item) once the i-th dynamic return is available OR the
        stream is terminal — at that point the generator's `__next__` is
        guaranteed non-blocking. Fires immediately if already satisfied.
        The async HTTP edge relays token streams with this instead of
        parking a thread per live stream."""
        with self._obj_lock:
            rec = self._dynamic_returns.get(task_id)
            if rec is None or i < len(rec["refs"]) or rec["done"]:
                satisfied = True
            else:
                rec.setdefault("waiters", []).append((i, cb))
                satisfied = False
        if satisfied:
            cb()

    @staticmethod
    def _drain_dynamic_waiters(rec) -> list:
        """Under _obj_lock: pop the waiters whose item (or terminal state)
        is now available; caller invokes them OUTSIDE the lock."""
        waiters = rec.get("waiters")
        if not waiters:
            return []
        n = len(rec["refs"])
        fire = [cb for i, cb in waiters if i < n or rec["done"]]
        if fire:
            rec["waiters"] = [(i, cb) for i, cb in waiters
                              if not (i < n or rec["done"])]
        return fire

    def make_dynamic_generator(self, gen_ref: ObjectRef) -> ObjectRefGenerator:
        """Owner-side streaming generator for a just-submitted dynamic task
        (holds gen_ref so the record and items outlive the submit call)."""
        g = ObjectRefGenerator([], task_id=gen_ref.id.task_id(), done=False)
        g._gen_ref = gen_ref
        return g

    def _finish_dynamic(self, task_id: TaskID, results) -> None:
        """Terminal report arrived for a (possibly) dynamic task: wake the
        streaming iterator, carrying the task error if it failed."""
        with self._obj_lock:
            rec = self._dynamic_returns.get(task_id)
            if rec is None or rec["done"]:
                return
            err = None
            for e in results:
                if e[0] == "error":
                    try:
                        err = serialization.loads(e[2])
                    except Exception:
                        err = TaskError("generator task failed")
            rec["done"] = True
            rec["error"] = err
            fire = self._drain_dynamic_waiters(rec)
            self._obj_cv.notify_all()
        for cb in fire:
            try:
                cb()
            except Exception:
                logger.exception("dynamic-return callback failed")

    def _report_dynamic(self, spec: TaskSpec, entry) -> None:
        """Deliver one streamed item to the owner. Raises on failure (after
        one reconnect retry): a silently-dropped item would leave a hole the
        completed generator still references — failing the whole task (the
        caller of this helper runs inside the executor's try) is the honest
        outcome, and retries/lineage can then re-run the generator."""
        payload = {"task_id": spec.task_id, "entry": entry}
        if spec.owner_address == self.address:
            self.rpc_report_dynamic_return(None, 0, payload)
            return
        try:
            self.peer(spec.owner_address).notify("report_dynamic_return", payload)
        except Exception:
            with self._peers_lock:  # stale conn: retry on a fresh one
                self._peers.pop(spec.owner_address, None)
            self.peer(spec.owner_address).notify("report_dynamic_return", payload)

    _PROBE_METHODS = frozenset({"health", "__ray_ready__", "__ray_terminate__"})

    def rpc_actor_stats(self, conn, req_id, payload):
        """Out-of-band load probe: executing + queued task counts, answered
        from the RPC thread so it can NOT be delayed by the exec queue it
        measures (Serve autoscaling reads this; cf. reference replicas
        pushing queue metrics to the controller out-of-band). `load` excludes
        control-plane probes (health checks) that would otherwise inflate
        every sample by the probe itself."""
        return {"executing": self._executing_count,
                "queued": self._task_queue.qsize(),
                "load": self._load_count}

    def rpc_owner_stats(self, conn, req_id, payload):
        """Live ownership footprint of this process (`ray_tpu jobs` dials
        each RUNNING job's driver for the per-job live numbers the GCS
        doesn't track centrally)."""
        with self._pending_lock:
            pending = len(self._pending_tasks)
        with self._obj_lock:
            owned = len(self._objects)
            owned_bytes = sum((st.size or 0)
                              for st in self._objects.values())
        return {"job_id": self.job_id.binary(), "pending_tasks": pending,
                "owned_objects": owned, "owned_bytes": owned_bytes}

    def rpc_task_spilled(self, conn, req_id, payload):
        """Raylet push: our task was spilled to another node. Recording the
        target is what lets node-level failure reach the owner — when that
        node dies whole (raylet included), no raylet survives to push
        task_worker_died, so the owner fails over on the GCS membership
        event instead (see _fail_tasks_on_node)."""
        task_id: TaskID = payload["task_id"]
        with self._pending_lock:
            if task_id in self._pending_tasks:
                self._task_locations[task_id] = payload["node_id"]
        self._ensure_nodes_subscribed()
        return True

    def _ensure_nodes_subscribed(self) -> None:
        """Lazy nodes-channel subscription: first spill only (workers).
        After the subscribe lands, one spaced reconciliation covers a node
        death that slipped into the subscribe race window."""
        with self._pending_lock:
            if self._nodes_subscribed:
                return
            self._nodes_subscribed = True
        try:
            self.gcs.call("subscribe", {"channels": ["nodes"],
                                        "origin": self.raylet_address})
        except Exception:
            with self._pending_lock:
                self._nodes_subscribed = False
            logger.warning("nodes-channel subscribe failed; relying on "
                           "reconciliation", exc_info=True)
            return
        t = threading.Timer(3.0, self._reconcile_task_locations)
        t.daemon = True
        t.start()

    def _fail_tasks_on_node(self, node_id: bytes, reason: str) -> None:
        """Node-death failover: every pending task last seen on `node_id`
        is treated exactly like a worker death there (retry budget applies).
        Popping the location first makes the event + reconciliation paths
        idempotent — a task only fails over once per (re)submission; its
        next spill records a fresh location."""
        with self._pending_lock:
            doomed = [tid for tid, loc in self._task_locations.items()
                      if loc == node_id]
            for tid in doomed:
                self._task_locations.pop(tid, None)
        for tid in doomed:
            logger.warning("task %s was on dead node %s; failing over",
                           tid, node_id.hex()[:8])
            self.rpc_task_worker_died(None, 0, {
                "task_id": tid, "reason": f"node died: {reason}"})

    def _reconcile_task_locations(self) -> None:
        """Post-reconnect backstop for missed node-removal events: compare
        recorded spill locations against the rebuilt GCS membership. A node
        PRESENT but dead fails over immediately; a node ABSENT might just
        not have re-registered yet (a fresh no-snapshot head starts empty),
        so absence only counts on the second spaced check."""
        with self._pending_lock:
            locs = {tid: loc for tid, loc in self._task_locations.items()}
        if not locs:
            return
        try:
            nodes = self.gcs.call("get_all_nodes", {}, timeout=10)
        except Exception:
            logger.debug("task-location reconcile fetch failed",
                         exc_info=True)
            return
        present = {n["node_id"]: n.get("alive", True) for n in nodes}
        rearm = False
        for node_id in set(locs.values()):
            alive = present.get(node_id)
            if alive is False:
                self._fail_tasks_on_node(node_id, "dead after GCS restart")
            elif alive is None:
                if node_id in self._absent_nodes:
                    self._absent_nodes.discard(node_id)
                    self._fail_tasks_on_node(
                        node_id, "gone after GCS restart")
                else:
                    # first strike: give the raylet one more window to
                    # re-register before declaring its tasks lost
                    self._absent_nodes.add(node_id)
                    rearm = True
            else:
                self._absent_nodes.discard(node_id)
        if rearm:
            t = threading.Timer(5.0, self._reconcile_task_locations)
            t.daemon = True
            t.start()

    def rpc_task_worker_died(self, conn, req_id, payload):
        """Raylet push: the worker running our task died. Retry or fail."""
        task_id: TaskID = payload["task_id"]
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
            if pend is None:
                return True
            self._task_locations.pop(task_id, None)
            spec = pend[0]
            retry = pend[1] > 0
            if retry:
                pend[1] -= 1
                retries_left = pend[1]
            else:
                self._pending_tasks.pop(task_id, None)
        if retry:
            logger.warning("task %s worker died (%s); retrying (%d left)",
                           spec.method_name, payload.get("reason") or "crash",
                           retries_left)
            self._resubmit_later(spec, get_config().task_retry_delay_ms / 1000.0)
            return True
        if payload.get("reason") == "cancelled":
            # force=True escalation: the raylet SIGKILLed the worker on our
            # cancel — non-retryable by construction (the cancel zeroed the
            # budget), resolved typed
            err_blob = serialization.dumps(TaskCancelledError(
                f"task {spec.method_name} was force-cancelled "
                f"(worker killed)"))
        elif payload.get("reason") == "oom":
            from ray_tpu.core.exceptions import OutOfMemoryError

            err_blob = serialization.dumps(OutOfMemoryError(
                f"task {spec.method_name} was killed by the memory monitor "
                f"under node memory pressure (retries exhausted)"))
        else:
            err_blob = serialization.dumps(WorkerCrashedError(
                f"worker died while running {spec.method_name}"))
        for oid in spec.return_object_ids():
            with self._obj_lock:
                st = self._objects.get(oid)
                if st is not None and st.state == "pending":
                    st.state = "error"
                    st.inline_blob = err_blob
                    self._obj_cv.notify_all()
            self._notify_info_waiters(oid)
        self._finish_dynamic(task_id, [("error", None, err_blob)])
        self._unpin_after_task(spec)
        return True

    def rpc_task_failed(self, conn, req_id, payload):
        """Raylet push: task cannot run (e.g. runtime-env creation failed).
        Deterministic — fail the returns without retrying."""
        task_id: TaskID = payload["task_id"]
        with self._pending_lock:
            pend = self._pending_tasks.pop(task_id, None)
            self._task_locations.pop(task_id, None)
        if pend is None:
            return True
        spec = pend[0]
        from ray_tpu.core.exceptions import RuntimeEnvSetupError

        err_blob = serialization.dumps(RuntimeEnvSetupError(payload["error"]))
        for oid in spec.return_object_ids():
            with self._obj_lock:
                st = self._objects.get(oid)
                if st is not None and st.state == "pending":
                    st.state = "error"
                    st.inline_blob = err_blob
                    self._obj_cv.notify_all()
            self._notify_info_waiters(oid)
        self._finish_dynamic(task_id, [("error", None, err_blob)])
        self._unpin_after_task(spec)
        return True

    def rpc_add_borrower(self, conn, req_id, payload):
        """Borrow registration, scoped to the borrower's CONNECTION: if the
        borrower process dies, its connection drop releases every borrow it
        held — a died borrower can no longer leak objects forever (the
        liveness role of the reference's WaitForRefRemoved long-polls,
        reference_count.h:834)."""
        oid = payload["object_id"]
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is None:
                return True
            st.borrowers += 1
            if conn is not None:
                key = id(conn)
                m = self._conn_borrows.get(key)
                if m is None:
                    m = self._conn_borrows[key] = {}
                    conn.on_close.append(
                        lambda c, k=key: self._on_borrower_conn_close(k))
                m[oid] = m.get(oid, 0) + 1
        return True

    def rpc_remove_borrower(self, conn, req_id, payload):
        """Symmetric to rpc_add_borrower: the decrement is honored only when
        THIS connection's map recorded the borrow. A remove arriving on a
        fresh connection after the old one's close already released the
        borrow must be a no-op — an unconditional decrement would free an
        object out from under a different live borrower."""
        oid = payload["object_id"]
        with self._obj_lock:
            recorded = conn is None  # internal calls bypass conn accounting
            if conn is not None:
                m = self._conn_borrows.get(id(conn))
                if m is not None and m.get(oid, 0) > 0:
                    recorded = True
                    left = m[oid] - 1
                    if left > 0:
                        m[oid] = left
                    else:
                        m.pop(oid, None)
            st = self._objects.get(oid)
            if st is not None and recorded:
                st.borrowers = max(0, st.borrowers - 1)
                self._maybe_free(oid, st)
        return True

    def rpc_remove_borrowers(self, conn, req_id, payload):
        """Batched rpc_remove_borrower: one notify releases many borrows
        (the borrower's owner-notify loop coalesces a GC storm per owner
        before it reaches the wire)."""
        for oid in payload["object_ids"]:
            self.rpc_remove_borrower(conn, req_id, {"object_id": oid})
        return True

    def _on_borrower_conn_close(self, conn_key: int) -> None:
        """The borrower's process (or its link) died: release every borrow
        registered over that connection."""
        with self._obj_lock:
            m = self._conn_borrows.pop(conn_key, None)
            if not m:
                return
            for oid, count in m.items():
                st = self._objects.get(oid)
                if st is not None:
                    st.borrowers = max(0, st.borrowers - count)
                    self._maybe_free(oid, st)
        logger.debug("released %d borrows from dead borrower connection",
                     sum(m.values()))

    # ------------------------------------------------------------- ref count
    def _remove_owned_local_ref(self, oid: ObjectID) -> None:
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is None:
                return
            st.local_refs -= 1
            self._maybe_free(oid, st)

    def add_local_ref(self, oid: ObjectID) -> None:
        with self._obj_lock:
            st = self._objects.get(oid)
            if st is not None:
                st.local_refs += 1

    def _maybe_free(self, oid: ObjectID, st: _ObjectState) -> None:
        """Caller holds _obj_lock. Free the object when fully unreferenced.

        Objects whose refs were serialized outward get a grace period before
        the plasma delete: a receiver's add_borrower notify may still be in
        flight when the owner's last local ref dies (the reference resolves
        this with the full borrow-table protocol, reference_count.h:834; the
        grace window + lineage recovery approximate it)."""
        if (st.local_refs > 0 or st.borrowers > 0
                or st.submitted_task_deps > 0 or st.container_pinned > 0):
            st.free_after = None
            return
        if st.state == "pending":
            return  # task still running; lineage bookkeeping keeps it
        if st.shipped and st.state in ("plasma", "inline"):
            # Inline objects race identically: the receiver's add_borrower
            # notify may be in flight when the owner's last ref dies.
            if st.free_after is None:
                grace_ms = get_config().object_free_grace_period_ms
                if oid not in self._lineage:
                    # No lineage means no reconstruction backstop (puts and
                    # actor returns, worker.py _register_returns): a borrow
                    # landing after the free would be an UNRECOVERABLE loss,
                    # so give the registration far longer to arrive — it may
                    # be stuck behind an owner-link reconnect backoff.
                    grace_ms *= 10
                st.free_after = time.monotonic() + grace_ms / 1000.0
                self._deferred_frees.append(oid)
                self._ensure_free_sweeper()
            return
        self._objects.pop(oid, None)
        self._release_contained_pins(st)
        self._drop_dynamic_record(oid)
        self._delete_plasma(oid, st)

    def _drop_dynamic_record(self, oid: ObjectID) -> None:
        """Caller holds _obj_lock. The first return object of a task was
        freed; if it was a generator's main object, drop the streaming
        record (its counted item refs release on GC)."""
        if oid.return_index() == 1:
            self._dynamic_returns.pop(oid.task_id(), None)

    def _release_contained_pins(self, st: _ObjectState) -> None:
        """Caller holds _obj_lock. The container object is gone: drop the
        pins it held on owned refs nested inside its payload, and the
        counted borrow refs for other-owned inner objects (their __del__
        notifies the owners off-thread)."""
        pins, st.contained_pins = st.contained_pins, []
        st.contained_borrows = []
        for inner in pins:
            ist = self._objects.get(inner)
            if ist is not None:
                ist.container_pinned = max(0, ist.container_pinned - 1)
                self._maybe_free(inner, ist)

    def _adopt_contained_refs(self, container_oid: ObjectID, contained) -> None:
        """A task return we own carries nested refs: keep each inner object
        alive for the CONTAINER's lifetime — a reader may deserialize the
        payload (registering its own borrow only then) arbitrarily late.
        Caller-owned inner refs get a container pin (like put()); refs owned
        elsewhere (e.g. the executing actor) get a counted borrow held by
        the container (reference nested-ref tracking, reference_count.h:834)."""
        borrows = []
        for ioid, iowner in contained:
            if iowner == self.address:
                with self._obj_lock:
                    cst = self._objects.get(container_oid)
                    ist = self._objects.get(ioid)
                    # a re-reported task (retry/reconstruction) must not
                    # double-pin: ids are deterministic across re-runs
                    if (cst is not None and ist is not None
                            and ioid != container_oid
                            and ioid not in cst.contained_pins):
                        ist.container_pinned += 1
                        cst.contained_pins.append(ioid)
            else:
                with self._obj_lock:
                    cst = self._objects.get(container_oid)
                    if cst is not None and any(
                            b.id == ioid for b in cst.contained_borrows):
                        continue  # re-report: borrow already held
                r = ObjectRef(ioid, owner_address=iowner)
                self.reference_counter.add_borrowed(r)
                r._counted = True
                borrows.append(r)
        if borrows:
            with self._obj_lock:
                cst = self._objects.get(container_oid)
                if cst is not None:
                    cst.contained_borrows.extend(borrows)
            # container already freed: `borrows` dies here and the refs'
            # __del__ releases the just-taken borrows

    def _delete_plasma(self, oid: ObjectID, st: _ObjectState) -> None:
        if st.state != "plasma":
            return
        for loc in [st.location] + st.extra_locations:
            if not loc:
                continue
            try:
                if loc == self.raylet_address:
                    self.raylet.notify("obj_delete", {"object_id": oid})
                else:
                    self.peer(loc).notify("obj_delete", {"object_id": oid})
            except OSError as e:
                # location holder died; its store died with it
                logger.debug("obj_delete to %s lost: %s", loc, e)

    # ------------------------------------------------------------- push
    def push_object(self, ref: ObjectRef, node_ids=None) -> int:
        """Owner-directed broadcast (reference push_manager.h:29): stream an
        owned, sealed plasma object into other nodes' stores AHEAD of
        demand, so N downstream readers hit a local copy instead of all
        pulling from one source. node_ids: restrict targets (hex or bytes
        node ids); None = every other alive node. Returns the number of
        push targets. Fire-and-forget: delivery registers new locations
        with this owner as copies land."""
        if ref.owner_address not in ("", self.address):
            raise ValueError("push() requires a ref owned by this process")
        with self._obj_lock:
            st = self._objects.get(ref.id)
            if st is None or st.state != "plasma" or not st.location:
                raise ValueError(
                    "push() needs a sealed plasma object (small objects are "
                    "inlined and need no push)")
            location = st.location
            have = {location, *st.extra_locations}
        if node_ids is not None:
            wanted = {n.hex() if isinstance(n, (bytes, bytearray)) else str(n)
                      for n in node_ids}
        targets = []
        for n in self.gcs.call("get_all_nodes", {}):
            if not n.get("alive") or n["address"] in have:
                continue
            if node_ids is not None:
                nid = n["node_id"]
                nid_hex = nid.hex() if isinstance(nid, (bytes, bytearray)) else str(nid)
                if nid_hex not in wanted:
                    continue
            targets.append(n["address"])
        if not targets:
            return 0
        payload = {"object_id": ref.id, "targets": targets,
                   "owner_address": self.address}
        if location == self.raylet_address:
            self.raylet.notify("push_object", payload)
        else:
            self.peer(location).notify("push_object", payload)
        try:
            from ray_tpu.util.metrics import get_or_create

            get_or_create("counter", "ray_tpu_push_requests_total",
                          "push() broadcasts dispatched").inc()
            get_or_create("counter", "ray_tpu_push_targets_total",
                          "cumulative push fan-out targets").inc(
                              len(targets))
        except (ValueError, KeyError) as e:
            logger.debug("push metrics unavailable: %s", e)
        return len(targets)

    def _notify_owner_async(self, owner: str, method: str, payload: dict) -> None:
        self._owner_notify_q.put((owner, method, payload))
        # The lock pairs with the loop's exit decision: either the live
        # thread sees our item (queue non-empty under the lock), or it has
        # cleared _owner_notify_thread and we start a fresh one — an item
        # can never be stranded behind a thread that decided to exit.
        with self._owner_notify_lock:
            t = self._owner_notify_thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._owner_notify_loop,
                                     name="owner-notify", daemon=True)
                self._owner_notify_thread = t
                t.start()

    def _owner_notify_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                item = self._owner_notify_q.get(timeout=5)
            except queue.Empty:
                with self._owner_notify_lock:
                    if self._owner_notify_q.empty():
                        self._owner_notify_thread = None
                        return  # idle: next release starts a fresh thread
                continue
            # Drain everything already queued: a GC storm's remove_borrower
            # releases coalesce into ONE batched notify per owner per drain
            # instead of one RPC per dropped ref (completion-path fast lane).
            items = [item]
            while True:
                try:
                    items.append(self._owner_notify_q.get_nowait())
                except queue.Empty:
                    break
            sends: List[Tuple[str, str, dict]] = []
            batches: Dict[str, list] = {}
            for owner, method, payload in items:
                if method == "remove_borrower":
                    b = batches.get(owner)
                    if b is None:
                        b = batches[owner] = []
                        sends.append((owner, "remove_borrowers",
                                      {"object_ids": b}))
                    b.append(payload["object_id"])
                else:
                    sends.append((owner, method, payload))
            for owner, method, payload in sends:
                try:
                    # Same link the borrow was registered over: the owner's
                    # conn-scoped accounting only honors removes that arrive
                    # on the connection that recorded the add.
                    self.reference_counter.owner_link(owner).notify(method, payload)
                except (OSError, RuntimeError, TimeoutError):
                    logger.debug("%s notify to %s failed", method, owner)

    def _ensure_free_sweeper(self) -> None:
        if self._free_sweeper is None or not self._free_sweeper.is_alive():
            t = threading.Thread(target=self._free_sweep_loop,
                                 name="free-sweeper", daemon=True)
            self._free_sweeper = t
            t.start()

    def _free_sweep_loop(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(0.1)
            due: List[Tuple[ObjectID, _ObjectState]] = []
            now = time.monotonic()
            with self._obj_lock:
                remaining: deque = deque()
                while self._deferred_frees:
                    oid = self._deferred_frees.popleft()
                    st = self._objects.get(oid)
                    if st is None or st.free_after is None:
                        continue  # resurrected or already freed
                    if st.free_after > now:
                        remaining.append(oid)
                        continue
                    if (st.local_refs > 0 or st.borrowers > 0
                            or st.submitted_task_deps > 0
                            or st.container_pinned > 0):
                        st.free_after = None  # a borrow landed within grace
                        continue
                    self._objects.pop(oid, None)
                    self._release_contained_pins(st)
                    self._drop_dynamic_record(oid)
                    due.append((oid, st))
                self._deferred_frees = remaining
                if not self._deferred_frees and not due:
                    # Nothing left: exit instead of idling forever. Cleared
                    # under _obj_lock, which every _ensure_free_sweeper caller
                    # holds, so a concurrent deferral can't miss the restart.
                    self._free_sweeper = None
                    return
            for oid, st in due:
                self._delete_plasma(oid, st)

    # --------------------------------------------------------------- actors
    def create_actor(self, spec: ActorCreationSpec, class_name: str) -> None:
        if spec.runtime_env and spec.runtime_env.get("py_modules"):
            from ray_tpu.runtime_env import upload_py_modules

            spec.runtime_env = upload_py_modules(spec.runtime_env, self.gcs)
        # owning job: the fate-sharing reap kills non-detached actors of a
        # dead job by this stamp (detached actors are GCS-owned and exempt)
        spec.job_id = self.job_id
        r = self.gcs.call("register_actor", {
            "spec": spec, "owner_address": self.address, "class_name": class_name})
        if isinstance(r, dict) and r.get("error"):
            raise ValueError(r["error"])

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
        concurrency_group: str = None,
    ) -> List[ObjectRef]:
        task_id = self._task_counter.next_task_id()
        with self._actor_seq_lock:
            seq = self._actor_seq_counters.get(actor_id, 0)
            self._actor_seq_counters[actor_id] = seq + 1
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.ACTOR_TASK,
            function_blob=None,
            method_name=method_name,
            args=self._serialize_args(args, task_id),
            kwargs_blob=serialization.dumps(kwargs) if kwargs else None,
            num_returns=num_returns,
            owner_address=self.address,
            owner_worker_id=self.worker_id,
            actor_id=actor_id,
            sequence_number=seq,
            caller_id=self.worker_id,
            concurrency_group=concurrency_group,
            parent_task_id=self._parent_for_submit(),
        )
        t_sub = self._stamp_trace_ctx(spec)
        refs = self._register_returns(spec)
        with self._pending_lock:
            self._pending_tasks[task_id] = [spec, 0]
        self._emit_task_event(spec, "SUBMITTED")
        self._send_actor_task(actor_id, spec, attempts=0)
        self._record_submit_span(spec, t_sub)
        return refs

    def _send_actor_task(self, actor_id: ActorID, spec: TaskSpec, attempts: int) -> None:
        dead_reason = self._actor_dead.get(actor_id)
        if dead_reason is not None:
            self._fail_task(spec, ActorDiedError(dead_reason))
            return
        addr = self._actor_addresses.get(actor_id)
        if addr is None:
            addr = self._wait_actor_address(actor_id, spec)
            if addr is None:
                return  # _fail_task already called
        # pin the call to the incarnation this address was learned with:
        # the target refuses a mismatch, so the call can never be serviced
        # by a superseded instance a partition kept alive (nor accepted by
        # a newer one the caller hasn't resolved yet)
        spec.actor_incarnation = self._actor_incarnations.get(actor_id)
        try:
            # short dial budget: this address came from a LIVE registration
            # (GCS state or a pubsub push), so a refused connect means the
            # actor's worker died — fail fast into the re-resolve path
            # below instead of spinning the full 30 s connect retry on a
            # corpse (a node kill makes every stale-address submit hit
            # this)
            self.peer(addr, connect_timeout_s=min(
                5.0, get_config().rpc_connect_timeout_s)).notify(
                    "push_actor_task", {"spec": spec})
        except Exception:
            # stale address: refresh once, then give up to GCS state
            self._actor_addresses.pop(actor_id, None)
            if attempts < 3:
                time.sleep(0.2 * (attempts + 1))
                self._send_actor_task(actor_id, spec, attempts + 1)
            else:
                self._fail_task(spec, ActorDiedError(
                    f"actor {actor_id} unreachable"))

    def _wait_actor_address(self, actor_id: ActorID, spec: TaskSpec,
                            timeout: float = 60.0) -> Optional[str]:
        """Wait for the actor to become ALIVE: pubsub pushes (drivers are
        subscribed to the actors channel) wake the condition variable
        instantly; an authoritative GCS poll runs as a 1 s fallback so
        non-subscribed workers still converge without hammering the GCS at
        the old 100 ms cadence."""
        deadline = time.monotonic() + timeout
        poll_next = 0.0
        while time.monotonic() < deadline:
            addr = self._actor_addresses.get(actor_id)
            if addr is not None:
                return addr
            dead = self._actor_dead.get(actor_id)
            if dead is not None:
                self._fail_task(spec, ActorDiedError(dead))
                return None
            now = time.monotonic()
            if now >= poll_next:
                poll_next = now + 1.0
                info = self.gcs.call("get_actor_info", {"actor_id": actor_id},
                                     timeout=10)
                if info is None:
                    self._fail_task(spec, ActorDiedError(f"actor {actor_id} unknown"))
                    return None
                if info["state"] == "ALIVE":
                    if info.get("incarnation") is not None:
                        self._actor_incarnations[actor_id] = \
                            info["incarnation"]
                    self._actor_addresses[actor_id] = info["address"]
                    return info["address"]
                if info["state"] == "DEAD":
                    self._actor_dead[actor_id] = info["death_cause"] or "actor died"
                    self._fail_task(spec, ActorDiedError(self._actor_dead[actor_id]))
                    return None
            with self._actor_cv:
                self._actor_cv.wait(timeout=0.1)
        self._fail_task(spec, ActorDiedError(f"timed out waiting for actor {actor_id}"))
        return None

    def _resubmit_later(self, spec: TaskSpec, delay: float) -> None:
        """Schedule a delayed task resubmission on the shared retry timer
        (one thread for all in-flight retry delays; started lazily, exits
        when the heap drains)."""
        with self._resubmit_cv:
            self._resubmit_seq += 1
            heapq.heappush(self._resubmit_heap,
                           (time.monotonic() + delay, self._resubmit_seq, spec))
            t = self._resubmit_thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._resubmit_loop,
                                     name="task-resubmit", daemon=True)
                self._resubmit_thread = t
                t.start()
            self._resubmit_cv.notify_all()

    def _resubmit_loop(self) -> None:
        while not self._shutdown.is_set():
            with self._resubmit_cv:
                if not self._resubmit_heap:
                    self._resubmit_cv.wait(timeout=1.0)
                    if not self._resubmit_heap:
                        # Exit decision under the cv: _resubmit_later holds it
                        # while pushing + checking liveness, so an item can
                        # never strand behind a thread that chose to exit.
                        self._resubmit_thread = None
                        return
                due, _, spec = self._resubmit_heap[0]
                wait = due - time.monotonic()
                if wait > 0:
                    self._resubmit_cv.wait(timeout=wait)
                    continue
                heapq.heappop(self._resubmit_heap)
            try:
                self.raylet.notify("submit_task", {"spec": spec})
            except Exception:
                logger.warning("delayed resubmit of %s lost (raylet down?)",
                               spec.method_name)

    def _fail_task(self, spec: TaskSpec, err: Exception) -> None:
        with self._pending_lock:
            self._pending_tasks.pop(spec.task_id, None)
            self._task_locations.pop(spec.task_id, None)
            if (spec.task_id in self._cancelled_tasks
                    and not isinstance(err, TaskCancelledError)):
                # once cancel() claimed the task, every failure path
                # resolves typed — an actor-death or timeout racing the
                # cancel must not change the contract
                err = TaskCancelledError(
                    f"task {spec.method_name} was cancelled ({err})")
        self._fence_resends.pop(spec.task_id, None)
        blob = serialization.dumps(err)
        for oid in spec.return_object_ids():
            with self._obj_lock:
                st = self._objects.get(oid)
                if st is not None:
                    st.state = "error"
                    st.inline_blob = blob
                    self._obj_cv.notify_all()
            self._notify_info_waiters(oid)
        self._finish_dynamic(spec.task_id, [("error", None, blob)])
        self._unpin_after_task(spec)

    # --------------------------------------------------------------- cancel
    def _parent_for_submit(self) -> Optional[TaskID]:
        """Lineage stamp for recursive cancellation: the task THIS thread
        was executing when it submitted (None for driver-root submits)."""
        cur = self._current_task_id
        return None if cur == self._root_task_id else cur

    def cancel(self, ref: ObjectRef, *, force: bool = False,
               recursive: bool = False) -> None:
        """Cancel the task producing `ref`. Best-effort on the work, hard
        guarantee on the ref: once claimed here, the ref resolves to
        TaskCancelledError — via raylet dequeue (still queued), cooperative
        interrupt (running; force=True escalates to SIGKILL through the
        worker-died path), actor-mailbox purge, or the local failsafe if
        every downstream ack is lost. A task that already completed keeps
        its value (reference semantics). recursive=True walks the lineage
        (parent_task_id) hop by hop so the whole tree dies leaf-ward."""
        self.cancel_task(ref.id.task_id(), force=force, recursive=recursive)

    def cancel_task(self, task_id: TaskID, *, force: bool = False,
                    recursive: bool = False) -> None:
        now = time.monotonic()
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
            already = task_id in self._cancelled_tasks
            if pend is None:
                return  # completed (value stands) or never ours: no-op
            self._cancelled_tasks[task_id] = now
            # opportunistic prune: the guard entries only matter while a
            # straggler report can still arrive
            if len(self._cancelled_tasks) > 64:
                for tid, ts in list(self._cancelled_tasks.items()):
                    if now - ts > 600.0 and tid not in self._pending_tasks:
                        del self._cancelled_tasks[tid]
            pend[1] = 0  # a cancelled task is never retried
            spec = pend[0]
            location = self._task_locations.get(task_id)
        if already:
            return  # double-cancel: the first claim owns resolution
        self._emit_task_event(spec, "CANCELLED")
        payload = {"task_id": task_id, "force": force,
                   "recursive": recursive, "owner_address": self.address}
        try:
            if spec.task_type == TaskType.ACTOR_TASK:
                # the call sits in the target actor's mailbox (queued) or on
                # one of its exec threads (running): cancel at the actor
                addr = self._actor_addresses.get(spec.actor_id)
                if addr is not None:
                    self.peer(addr, connect_timeout_s=min(
                        5.0, get_config().rpc_connect_timeout_s)).notify(
                            "cancel_task", payload)
                else:
                    # still parked on actor resolution: nothing downstream
                    # holds it — resolve right here
                    self._fail_cancelled(spec)
                    return
            else:
                if location is not None:
                    # spilled: our raylet forwards to the node holding it
                    payload["spilled_node_id"] = location
                self.raylet.notify("cancel_task", payload)
        except Exception:
            logger.debug("cancel notify for %s lost", task_id, exc_info=True)
        # Failsafe: a cancelled ref may NEVER hang. If no downstream ack
        # (dequeue notify, cooperative error report, kill report) resolves
        # the ref within the window, resolve it typed locally.
        t = threading.Timer(get_config().task_cancel_resolution_timeout_s,
                            self._cancel_failsafe, args=(task_id,))
        t.daemon = True
        t.start()

    def _cancel_failsafe(self, task_id: TaskID) -> None:
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
        if pend is None:
            return
        logger.warning(
            "cancel of %s got no downstream resolution within %.1fs; "
            "resolving locally", pend[0].method_name,
            get_config().task_cancel_resolution_timeout_s)
        self._fail_cancelled(pend[0], "cancelled (no executor ack)")

    def _fail_cancelled(self, spec: TaskSpec, detail: str = "") -> None:
        self._fail_task(spec, TaskCancelledError(
            detail or f"task {spec.method_name} was cancelled"))

    def rpc_task_cancelled(self, conn, req_id, payload):
        """Raylet ack: the task was dequeued (or purged in a job reap)
        before running — resolve its refs to the typed error."""
        task_id: TaskID = payload["task_id"]
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
            self._cancelled_tasks.setdefault(task_id, time.monotonic())
        if pend is not None:
            self._fail_cancelled(pend[0], payload.get("detail") or "")
        return True

    def rpc_cancel_task(self, conn, req_id, payload):
        """Executor-side cancel (pushed by an owner at the hosting actor's
        address, or relayed by our raylet for a plain task running here)."""
        self._handle_exec_cancel(payload["task_id"],
                                 force=bool(payload.get("force")),
                                 recursive=bool(payload.get("recursive")),
                                 owner_address=payload.get("owner_address"))
        return True

    def _handle_exec_cancel(self, task_id: TaskID, *, force: bool,
                            recursive: bool,
                            owner_address: Optional[str] = None) -> None:
        """This PROCESS hosts the task (queued in a mailbox/exec queue, or
        running on an exec thread): cancel it, children first."""
        if recursive:
            # tasks WE submitted while executing task_id are our pending
            # entries stamped with it as parent — full owner-side cancel
            # for each (they may be queued here, remote, or actor calls)
            with self._pending_lock:
                kids = [tid for tid, (spec, _r) in self._pending_tasks.items()
                        if spec.parent_task_id == task_id]
            for kid in kids:
                try:
                    self.cancel_task(kid, force=force, recursive=True)
                except Exception:
                    logger.debug("recursive cancel of child %s failed",
                                 kid, exc_info=True)
        with self._cancel_lock:
            self._cancelled_exec.add(task_id)
            thread_ident = self._exec_thread_ids.get(task_id)
        if thread_ident is not None:
            self._inject_cancel(task_id, thread_ident)
        elif owner_address:
            # Mailbox purge: the call is parked in this process's exec
            # queue (possibly behind a long-running method) and nothing
            # reports for it until it would have been dequeued — resolve
            # the owner's ref NOW. The eventual precancelled dequeue ships
            # a duplicate typed error the owner drops as a straggler.
            try:
                self.peer(owner_address, connect_timeout_s=min(
                    5.0, get_config().rpc_connect_timeout_s)).notify(
                        "task_cancelled",
                        {"task_id": task_id,
                         "detail": "cancelled while queued (mailbox purge)"})
            except Exception:
                logger.debug("mailbox-purge ack to %s lost", owner_address,
                             exc_info=True)

    def _inject_cancel(self, task_id: TaskID, thread_ident: int) -> None:
        """Cooperative interruption of a RUNNING task: raise
        TaskCancelledError inside the executing thread at its next bytecode
        boundary (a task parked in a long C call only observes it on
        return — force=True exists for those). The exec loop also guards
        against an injection landing after the task finished."""
        import ctypes

        res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident),
            ctypes.py_object(TaskCancelledError))
        if res > 1:
            # invalid state: undo so an unrelated thread isn't poisoned
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(thread_ident), None)
        logger.info("injected cancel into thread running task %s", task_id)

    def _log_print_queue(self) -> "queue.Queue":
        q = getattr(self, "_log_queue", None)
        if q is None:
            q = queue.Queue()
            self._log_queue = q

            def printer():
                import sys as _sys

                while not self._shutdown.is_set():
                    try:
                        msg = q.get(timeout=0.5)
                    except queue.Empty:
                        continue
                    out = (_sys.stderr if msg.get("stream") == "stderr"
                           else _sys.stdout)
                    for line in msg.get("lines", []):
                        print(f"(pid={msg.get('pid')}) {line}", file=out)

            threading.Thread(target=printer, name="log-printer",
                             daemon=True).start()
        return q

    def _resolve_gcs_address(self) -> Optional[str]:
        """Current-best GCS address for a reconnect attempt (control-plane
        HA): the address file when configured, else ask our raylet — its
        own reconnect loop follows a promoted/replacement head, so its
        answer is the freshest in-band source. None = keep the last-known
        address and retry; an EMPTY answer (torn address file mid-failover,
        a raylet with nothing better than our own guess) is never treated
        as an address to dial."""
        addr = rpc.read_gcs_address_file()
        if addr:
            return addr
        raylet = getattr(self, "raylet", None)
        if raylet is not None and not raylet.closed:
            try:
                return raylet.call("get_gcs_address", {}, timeout=2) or None
            except Exception:
                pass
        return None

    def _replay_gcs_state(self, raw: rpc.RpcClient) -> None:
        """Rebuild this process's GCS-side state after a GCS restart (uses
        the RAW client — the reconnecting wrapper's lock is held)."""
        # the link may have followed a head replacement to a new address
        self.gcs_address = raw.address
        # re-export the function table entries this process owns: a fresh
        # GCS (no snapshot) must still resolve ids from in-flight specs
        self.function_table.replay_exports(raw)
        if self.mode == "driver":
            raw.call("register_job", {
                "job_id": self.job_id.binary(),
                "driver_address": self._server.address,
            }, timeout=30)
            channels = ["actors", "nodes"]
            if self.log_to_driver:
                channels.append("logs")
            raw.call("subscribe", {"channels": channels,
                                   "origin": self.raylet_address},
                     timeout=30)
        else:
            # workers subscribe to the nodes channel LAZILY (first spill
            # only — see _nodes_subscribed): re-establish the subscription
            # across the reconnect only if it existed; an unconditional
            # subscribe would make every warm-forked worker a permanent
            # nodes-channel fan-out target after any head failover
            with self._pending_lock:
                resub = self._nodes_subscribed
            if resub:
                raw.call("subscribe", {"channels": ["nodes"],
                                       "origin": self.raylet_address},
                         timeout=30)
        # The reconnect window may have swallowed node-removal events for
        # nodes holding our spilled tasks (the classic pairing: node death
        # AND a GCS restart). Reconcile the location table against the
        # rebuilt membership off-thread, after re-registrations settle.
        with self._pending_lock:
            has_locs = bool(self._task_locations)
        if has_locs:
            t = threading.Timer(3.0, self._reconcile_task_locations)
            t.daemon = True
            t.start()
        with self._channel_cb_lock:
            dynamic = [ch for ch, cbs in self._channel_callbacks.items() if cbs]
        if dynamic:
            raw.call("subscribe", {"channels": dynamic,
                                   "origin": self.raylet_address},
                     timeout=30)
        if self.actor_id is not None and self._actor_instance is not None:
            spec = self._actor_creation_spec
            reply = raw.call("reregister_actor", {
                "actor_id": self.actor_id,
                "address": self.address,
                "node_id": self.node_id,
                "incarnation": self._actor_incarnation,
                "spec": spec,
            }, timeout=30)
            if isinstance(reply, dict) and reply.get("fenced"):
                # our incarnation was superseded while this process was
                # unreachable (the actor lives elsewhere now): exit rather
                # than ever answering a call again
                logger.warning(
                    "actor %s incarnation %d fenced at re-register: %s — "
                    "exiting", self.actor_id, self._actor_incarnation,
                    reply.get("reason"))
                self._fenced_exit()
                return
            logger.info("actor %s re-registered with restarted GCS",
                        self.actor_id)

    # ---------------------------------------------------------- app pubsub
    def subscribe_channel(self, channel: str, callback) -> None:
        """Subscribe to an application pubsub channel; `callback(message)`
        runs on the GCS push reader thread (keep it non-blocking). Survives
        GCS restart: dynamic channels are replayed on re-subscribe."""
        with self._channel_cb_lock:
            cbs = self._channel_callbacks.setdefault(channel, [])
            first = not cbs
            cbs.append(callback)
        if first:
            self.gcs.call("subscribe", {"channels": [channel],
                                        "origin": self.raylet_address},
                          timeout=30)

    def unsubscribe_channel(self, channel: str, callback) -> None:
        with self._channel_cb_lock:
            cbs = self._channel_callbacks.get(channel, [])
            if callback in cbs:
                cbs.remove(callback)
            empty = not cbs
            if empty:
                self._channel_callbacks.pop(channel, None)
        if empty:
            try:  # drop the GCS-side fan-out entry too
                self.gcs.notify("unsubscribe", {"channels": [channel]})
            except OSError as e:
                logger.debug("unsubscribe lost (GCS down?): %s", e)

    def publish(self, channel: str, message) -> None:
        self.gcs.notify("publish", {"channel": channel, "message": message})

    def _on_gcs_push(self, method: str, payload) -> None:
        if method != "pubsub":
            return
        with self._channel_cb_lock:
            cbs = list(self._channel_callbacks.get(payload["channel"], ()))
        for cb in cbs:
            try:
                cb(payload["message"])
            except Exception:
                logger.exception("pubsub callback failed on %s",
                                 payload["channel"])
        if payload["channel"] == "logs":
            msg = payload["message"]
            # only this driver's job (unattributed lines pass through);
            # printed from a dedicated thread so a blocked stdout can't
            # stall the rpc reader that also carries actor updates
            job = msg.get("job_id")
            if job is not None and job != self.job_id.binary():
                return
            self._log_print_queue().put(msg)
            return
        if payload["channel"] == "nodes":
            msg = payload["message"]
            if msg.get("event") == "removed":
                self._fail_tasks_on_node(msg["node_id"],
                                         msg.get("reason") or "node removed")
            return
        if payload["channel"] == "actors":
            msg = payload["message"]
            aid = msg["actor_id"]
            state = msg["state"]
            if state == "ALIVE":
                if msg.get("incarnation") is not None:
                    self._actor_incarnations[aid] = msg["incarnation"]
                self._actor_addresses[aid] = msg["address"]
                self._actor_dead.pop(aid, None)
            elif state == "DEAD":
                self._actor_addresses.pop(aid, None)
                self._actor_incarnations.pop(aid, None)
                self._actor_dead[aid] = msg.get("death_cause") or "actor died"
                self._fail_inflight_actor_tasks(aid, self._actor_dead[aid])
            else:  # RESTARTING: old incarnation's in-flight tasks are lost,
                # and the fresh incarnation expects sequence numbers from 0.
                self._actor_addresses.pop(aid, None)
                self._actor_incarnations.pop(aid, None)
                with self._actor_seq_lock:
                    self._actor_seq_counters.pop(aid, None)
                self._fail_inflight_actor_tasks(
                    aid, "actor restarting; in-flight call lost")
            with self._actor_cv:
                self._actor_cv.notify_all()

    def _fail_inflight_actor_tasks(self, actor_id: ActorID, reason: str) -> None:
        """The actor process died: calls sent to it will never report back.
        Fail their pending return objects so ray.get() unblocks."""
        with self._pending_lock:
            doomed = [spec for spec, _r in self._pending_tasks.values()
                      if spec.task_type == TaskType.ACTOR_TASK
                      and spec.actor_id == actor_id]
        for spec in doomed:
            self._fail_task(spec, ActorDiedError(reason))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.gcs.call("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})

    def get_actor_info(self, actor_id: Optional[ActorID] = None,
                       name: Optional[str] = None, namespace: str = ""):
        payload: dict = {}
        if name is not None:
            payload = {"name": name, "namespace": namespace}
        else:
            payload = {"actor_id": actor_id}
        return self.gcs.call("get_actor_info", payload)

    # ------------------------------------------------------------- execution
    def _on_raylet_push(self, method: str, payload) -> None:
        if method == "execute_task":
            spec = payload["spec"]
            ids = payload.get("tpu_ids")
            if ids:
                self._task_tpu_ids[spec.task_id] = list(ids)
            d_us = payload.get("dispatch_us")
            if d_us is not None and spec.trace_ctx is not None:
                # raylet's dispatch stamp: _execute_task turns it into the
                # dispatch-stage span (push -> execution start)
                self._task_dispatch_us[spec.task_id] = d_us
            self._task_queue.put(spec)
        elif method == "become_actor":
            self._actor_tpu_ids = list(payload.get("tpu_ids") or [])
            self._become_actor(payload["spec"],
                               payload.get("incarnation"))
        elif method == "cancel_task":
            self._handle_exec_cancel(payload["task_id"],
                                     force=bool(payload.get("force")),
                                     recursive=bool(payload.get("recursive")))
        elif method == "global_gc":
            import gc

            gc.collect()
        elif method == "profile":
            # on-demand cpu/memory profile of this worker (reference
            # dashboard py-spy/memray role); runs in a daemon thread and
            # drops its result file for the raylet to serve
            from ray_tpu.util.profiler import run_profile_request

            run_profile_request(payload)
        elif method == "exit":
            logger.info("worker exiting on raylet request")
            try:
                self.result_buffer.stop()
                self.task_events.flush()
            except Exception:
                pass
            os._exit(0)

    def _actor_group_for(self, spec: TaskSpec) -> Optional[str]:
        """Concurrency group for an actor call: the call-site override
        (method.options(concurrency_group=...)) wins, else the method's
        @method(concurrency_group=...) annotation; unknown names fall back
        to the default pool rather than stranding the call."""
        group = spec.concurrency_group
        if group is None and self._actor_instance is not None:
            fn = getattr(type(self._actor_instance), spec.method_name, None)
            group = getattr(fn, "_ray_tpu_method_opts", {}).get(
                "concurrency_group")
        if group is not None and group not in self._group_queues:
            # a typo'd group must FAIL the call, not silently land in the
            # default pool it was trying to escape (reference raises too)
            raise ValueError(
                f"actor has no concurrency group {group!r} "
                f"(declared: {sorted(self._group_queues) or 'none'})")
        return group

    def _enqueue_actor_task(self, spec: TaskSpec) -> None:
        # Load accounting happens HERE — only for tasks that actually enter
        # the exec queue (the matching decrement runs at execution end);
        # duplicate/stranded pushes must not inflate the load reading.
        if spec.method_name not in self._PROBE_METHODS:
            with self._exec_count_lock:
                self._load_count += 1
        try:
            group = self._actor_group_for(spec)
        except ValueError as e:
            # report the error to the caller's return objects; raising in
            # the push handler would vanish silently (pushes have no reply)
            with self._exec_count_lock:
                if spec.method_name not in self._PROBE_METHODS:
                    self._load_count -= 1  # undo the accounting above
            blob = serialization.dumps(
                TaskError.from_exception(spec.method_name, e))
            results = [("error", oid, blob)
                       for oid in spec.return_object_ids()]
            try:
                if spec.owner_address == self.address:
                    self.rpc_report_task_result(
                        None, 0, {"task_id": spec.task_id,
                                  "results": results})
                else:
                    self.peer(spec.owner_address).notify(
                        "report_task_result",
                        {"task_id": spec.task_id, "results": results})
            except Exception:
                logger.warning("could not report bad-group error for %s",
                               spec.method_name)
            return
        (self._group_queues[group] if group else self._task_queue).put(spec)

    def rpc_push_actor_task(self, conn, req_id, payload) -> None:
        """Direct actor transport target (callers push here). Incarnation
        fence first: a call pinned to a different incarnation than the one
        this process instantiates is REFUSED — the caller re-resolves and
        resends (rpc_actor_call_fenced) — and a call pinned to a NEWER
        incarnation additionally proves this process is a superseded
        zombie (its actor was restarted elsewhere during a partition): it
        self-terminates instead of ever answering again."""
        spec: TaskSpec = payload["spec"]
        pinned = getattr(spec, "actor_incarnation", None)
        if pinned is not None and spec.actor_id is not None \
                and (spec.actor_id != self.actor_id
                     or pinned != self._actor_incarnation):
            self._refuse_fenced_call(spec, pinned)
            return
        caller = spec.caller_id.binary() if spec.caller_id else b""
        with self._actor_seq_lock:
            expected = self._actor_next_seq.get(caller, 0)
            if spec.sequence_number == expected:
                self._actor_next_seq[caller] = expected + 1
                self._enqueue_actor_task(spec)
                # flush any buffered successors
                buf = self._actor_ooo_buffer.get(caller, {})
                nxt = expected + 1
                while nxt in buf:
                    self._enqueue_actor_task(buf.pop(nxt))
                    self._actor_next_seq[caller] = nxt + 1
                    nxt += 1
            else:
                self._actor_ooo_buffer.setdefault(caller, {})[spec.sequence_number] = spec

    def _fenced_exit(self) -> None:
        """This process was proven a SUPERSEDED actor incarnation: flush
        the delivery buffers and exit off-thread (callers sit on RPC
        reader / reconnect-lock paths), never to answer again."""
        def die():
            try:
                self.result_buffer.stop()
                self.task_events.flush()
            except Exception:
                pass
            os._exit(0)

        threading.Thread(target=die, name="fenced-exit",
                         daemon=True).start()

    def _refuse_fenced_call(self, spec: TaskSpec, pinned: int) -> None:
        """Executor side of the incarnation fence: tell the owner (it
        re-resolves and resends), then — if the call proves a NEWER
        incarnation exists — terminate this superseded instance."""
        superseded = (spec.actor_id == self.actor_id
                      and pinned > self._actor_incarnation)
        logger.warning(
            "refusing actor call %s pinned to incarnation %s (this worker "
            "instantiates %s of %s)%s", spec.method_name, pinned,
            self._actor_incarnation, self.actor_id,
            " — superseded, terminating" if superseded else "")
        try:
            self.peer(spec.owner_address).notify("actor_call_fenced", {
                "task_id": spec.task_id, "actor_id": spec.actor_id,
                "pinned": pinned, "actual": self._actor_incarnation})
        except Exception:
            logger.debug("fence notify to owner %s lost",
                         spec.owner_address, exc_info=True)
        if superseded:
            # the cluster moved past us while we were partitioned; exit
            # before any stale state can answer (raylet-side fencing kills
            # us too — this is the faster, call-triggered path)
            self._fenced_exit()

    def rpc_actor_call_fenced(self, conn, req_id, payload):
        """Owner side: the target refused our call's incarnation pin. The
        cached (address, incarnation) is stale — drop it, re-resolve from
        the GCS and resend with a fresh sequence number (ordering against
        the refused send is void: nothing executed). Bounded per task; a
        call that keeps getting fenced fails typed."""
        task_id: TaskID = payload["task_id"]
        actor_id = payload["actor_id"]
        with self._pending_lock:
            pend = self._pending_tasks.get(task_id)
        if pend is None:
            return True  # already failed/completed elsewhere
        spec = pend[0]
        resends = self._fence_resends.get(task_id, 0)
        if resends >= 3:
            self._fence_resends.pop(task_id, None)
            self._fail_task(spec, ActorDiedError(
                f"actor {actor_id} fenced call {resends + 1}x "
                f"(cluster incarnation view never converged)"))
            return True
        self._fence_resends[task_id] = resends + 1
        pinned = payload.get("pinned")
        with self._actor_seq_lock:
            cached_inc = self._actor_incarnations.get(actor_id)
            if cached_inc is not None and (pinned is None
                                           or cached_inc == pinned):
                # the cache still holds the STALE view this fence reports:
                # invalidate it once and restart the per-caller sequence —
                # the re-resolve lands on a new incarnation that expects 0.
                # A later fence for the same stale view finds the cache
                # already refreshed (cached != pinned) or empty and keeps
                # counting, so two fenced tasks can never both take seq 0.
                self._actor_addresses.pop(actor_id, None)
                self._actor_incarnations.pop(actor_id, None)
                self._actor_seq_counters.pop(actor_id, None)
            seq = self._actor_seq_counters.get(actor_id, 0)
            self._actor_seq_counters[actor_id] = seq + 1
            spec.sequence_number = seq

        def resend():
            self._send_actor_task(actor_id, spec, attempts=0)

        # off the push reader thread: _send_actor_task may block resolving
        threading.Thread(target=resend, name="fenced-resend",
                         daemon=True).start()
        return True

    @property
    def placement_group_id(self):
        """PG of the currently-executing task, else the hosting actor's PG."""
        pg = getattr(self._tls, "placement_group_id", None)
        if pg is not None:
            return pg
        spec = self._actor_creation_spec
        return spec.scheduling.placement_group_id if spec is not None else None

    def _become_actor(self, spec: ActorCreationSpec,
                      incarnation: Optional[int] = None) -> None:
        self.actor_id = spec.actor_id
        # set BEFORE callers can learn our address (creation_done comes
        # later): every arriving call is fence-checked against this
        if incarnation is None:
            incarnation = getattr(spec, "incarnation", 0)
        self._actor_incarnation = int(incarnation or 0)
        self._actor_creation_spec = spec
        threading.Thread(target=self._init_actor, args=(spec,), daemon=True).start()

    def _init_actor(self, spec: ActorCreationSpec) -> None:
        try:
            # become_actor can be pushed before our register reply lands.
            self._registered.wait(timeout=30)
            cls = self.function_table.resolve(
                getattr(spec, "class_fn_id", None), spec.class_blob)
            args, kwargs = self._deserialize_args(spec.init_args, spec.init_kwargs_blob)
            if spec.runtime_env:
                self._apply_runtime_env(spec.runtime_env)
            self._actor_instance = cls(*args, **kwargs)
            # dedicated pools BEFORE creation_done: callers only learn our
            # address afterwards, so no task can race an unrouted group
            for gname, gsize in (spec.concurrency_groups or {}).items():
                q: "queue.Queue[TaskSpec]" = queue.Queue()
                self._group_queues[gname] = q
                with self._exec_threads_lock:
                    for _ in range(max(1, int(gsize))):
                        self._spawn_exec_thread(q, f"task-exec-{gname}")
            self._start_exec_threads(max(1, spec.max_concurrency))
            # spec included so a GCS that restarted DURING our __init__ (and
            # so never saw the registration) can rebuild the actor record;
            # incarnation lets it reject a SUPERSEDED dispatch completing
            # late (the actor was restarted elsewhere mid-partition)
            self.gcs.call("actor_creation_done", {
                "actor_id": spec.actor_id, "success": True,
                "address": self.address, "node_id": self.node_id,
                "incarnation": self._actor_incarnation,
                "spec": spec})
        except Exception as e:
            logger.exception("actor creation failed")
            self.gcs.call("actor_creation_done", {
                "actor_id": spec.actor_id, "success": False,
                "error": f"{e}\n{traceback.format_exc()}"})

    def _apply_runtime_env(self, env: dict) -> None:
        import sys as _sys

        for k, v in env.get("env_vars", {}).items():
            os.environ[k] = str(v)
        if env.get("working_dir"):
            os.chdir(env["working_dir"])
        if env.get("py_modules"):
            from ray_tpu.runtime_env import ensure_py_modules

            cache = os.path.expanduser("~/.cache/ray_tpu/py_modules")
            os.makedirs(cache, exist_ok=True)
            for path in ensure_py_modules(env, self.gcs, cache):
                if path not in _sys.path:
                    _sys.path.insert(0, path)

    def _start_exec_threads(self, n: int) -> None:
        # Must be mutually exclusive: for an actor worker this is reached from
        # BOTH __init__ (mode=="worker") and the _init_actor thread; without
        # the lock each can observe len() < n and over-spawn, after which a
        # max_concurrency=1 actor executes queued calls concurrently and the
        # per-caller FIFO guarantee (reference
        # transport/actor_scheduling_queue.h) is violated.
        with self._exec_threads_lock:
            while len(self._default_exec_threads) < n:
                self._spawn_exec_thread(self._task_queue, "task-exec",
                                        self._default_exec_threads)

    def _spawn_exec_thread(self, q: "queue.Queue", name: str,
                           tracking: Optional[List[threading.Thread]] = None
                           ) -> None:
        """Caller holds _exec_threads_lock."""
        t = threading.Thread(target=self._exec_loop, args=(q,),
                             name=name, daemon=True)
        t.start()
        if tracking is not None:
            tracking.append(t)

    def _exec_loop(self, q: Optional["queue.Queue"] = None) -> None:
        q = q if q is not None else self._task_queue
        while not self._shutdown.is_set():
            try:
                spec = q.get(timeout=0.2)
            except queue.Empty:
                continue
            except TaskCancelledError:
                # an interrupt injected in the window after its task
                # finished lands here: the thread must survive it
                continue
            try:
                self._execute_task(spec)
            except TaskCancelledError:
                # injection raced the task's finally block; the task's own
                # except path already reported — keep the thread alive
                continue

    def _execute_task(self, spec: TaskSpec) -> None:
        """Run one task and route results to its owner
        (cf. reference `_raylet.pyx:718 execute_task`)."""
        prev_task_id = getattr(self._tls, "task_id", None)
        self._tls.task_id = spec.task_id
        self._tls.job_id = spec.job_id  # log attribution (tee -> driver)
        prev_pg = getattr(self._tls, "placement_group_id", None)
        self._tls.placement_group_id = spec.scheduling.placement_group_id
        # chip grant for get_tpu_ids(): the task's own, else the actor's
        self._tls.tpu_ids = self._task_tpu_ids.pop(
            spec.task_id, None) or list(self._actor_tpu_ids)
        # adopt the submitter's trace context: the execute/result spans —
        # and any task this task submits — join the same causal tree
        prev_ctx = tracing.current_ctx()
        traced = spec.trace_ctx is not None and tracing.enabled()
        if traced:
            tracing.set_ctx(spec.trace_ctx)
            d_us = self._task_dispatch_us.pop(spec.task_id, None)
            if d_us is not None:
                # dispatch stage: raylet push -> execution start (epoch-
                # anchored stamps; same-host clocks agree, cross-node skew
                # is corrected at merge from the clock-probe offsets)
                tracing.add_complete(
                    f"dispatch::{spec.method_name}", "task_dispatch",
                    d_us, tracing.now_us() - d_us,
                    trace_id=spec.trace_ctx[0],
                    parent_id=spec.trace_ctx[1],
                    task_id=spec.task_id.binary().hex())
        else:
            self._task_dispatch_us.pop(spec.task_id, None)
        self._emit_task_event(spec, "RUNNING")
        with self._exec_count_lock:
            self._executing_count += 1
        # cancellation: a task purged while queued (actor mailbox, exec
        # queue) reports typed WITHOUT running; a task that starts registers
        # its thread so a later cancel can inject the interrupt into it
        with self._cancel_lock:
            precancelled = spec.task_id in self._cancelled_exec
            if not precancelled:
                self._exec_thread_ids[spec.task_id] = threading.get_ident()
        failed = False
        results = []
        try:
            if precancelled:
                raise TaskCancelledError(
                    f"task {spec.method_name} was cancelled before execution")
            if spec.task_type == TaskType.ACTOR_TASK:
                if spec.method_name == "__ray_terminate__":
                    self.result_buffer.stop()
                    self.task_events.flush()
                    os._exit(0)
                fn = getattr(self._actor_instance, spec.method_name)
            else:
                # LRU of deserialized functions, GCS fetch on miss — the
                # executor half of the export-once fast lane (replaces a
                # cloudpickle.loads of the full blob on EVERY execution)
                fn = self.function_table.resolve(
                    spec.function_id, spec.function_blob)
                if spec.runtime_env:
                    self._apply_runtime_env(spec.runtime_env)
            args, kwargs = self._deserialize_args(spec.args, spec.kwargs_blob)
            with tracing.span(f"task::{spec.method_name}",
                              "task_execution",
                              task_id=spec.task_id.binary().hex()):
                value = fn(*args, **kwargs)
            if inspect.isasyncgen(value):
                raise TypeError(
                    "async generator returns are not supported; collect "
                    "results into a list inside the task")
            if inspect.iscoroutine(value):
                # async tasks / actor methods (reference async actors): one
                # PERSISTENT event loop per exec thread, so loop-bound actor
                # state (asyncio.Lock/Queue created in one call) stays valid
                # across calls. With max_concurrency=1 every call shares the
                # single loop, matching the reference's semantics.
                loop = getattr(self._tls, "aio_loop", None)
                if loop is None or loop.is_closed():
                    loop = asyncio.new_event_loop()
                    self._tls.aio_loop = loop
                with tracing.span(f"task::{spec.method_name}::await",
                                  "task_execution",
                                  task_id=spec.task_id.binary().hex()):
                    value = loop.run_until_complete(value)
            if spec.num_returns == -1:
                # Generator task: stream each yielded object to the owner AS
                # PRODUCED (reference streaming generators, _raylet.pyx:178);
                # the main return materializes afterwards as a completed
                # ObjectRefGenerator so borrowers get the full sequence.
                value = self._stream_dynamic_returns(spec, value)
                values = [value]
            elif spec.num_returns == 1:
                values = [value]
            else:
                values = list(value)
                if len(values) != spec.num_returns:
                    raise ValueError(
                        f"task declared num_returns={spec.num_returns} but returned "
                        f"{len(values)} values")
            # Own refs nested in a return value (e.g. an actor handing out
            # refs to objects it created) escape to the caller. Their
            # descriptors ship WITH the result so the caller — who owns the
            # enclosing return object — can keep them alive for the
            # container's lifetime (pin if caller-owned, borrow otherwise),
            # mirroring put()'s container pins.
            for oid, v in zip(spec.return_object_ids(), values):
                results.append(self._build_result_entry(oid, v))
        except TaskCancelledError as e:
            # ships the typed error DIRECTLY (not wrapped in TaskError):
            # the owner's ref must resolve to TaskCancelledError by type
            blob = serialization.dumps(TaskCancelledError(
                str(e) or f"task {spec.method_name} was cancelled"))
            results = [("error", oid, blob) for oid in spec.return_object_ids()]
            failed = True
        except Exception as e:
            from ray_tpu.core.exceptions import ActorError
            cls = ActorError if spec.task_type == TaskType.ACTOR_TASK else TaskError
            te = cls.from_exception(spec.method_name, e)
            blob = serialization.dumps(te)
            results = [("error", oid, blob) for oid in spec.return_object_ids()]
            failed = True
        finally:
            with self._cancel_lock:
                self._exec_thread_ids.pop(spec.task_id, None)
                self._cancelled_exec.discard(spec.task_id)
            if traced:
                tracing.set_ctx(prev_ctx)
            if prev_task_id is None:
                del self._tls.task_id
            else:
                self._tls.task_id = prev_task_id
            self._tls.placement_group_id = prev_pg
            with self._exec_count_lock:
                self._executing_count -= 1
                if (spec.task_type == TaskType.ACTOR_TASK
                        and spec.method_name not in self._PROBE_METHODS):
                    self._load_count -= 1
        self._emit_task_event(spec, "FAILED" if failed else "FINISHED")
        t_res = tracing.now_us() if traced else 0.0
        try:
            if spec.owner_address == self.address:
                self.rpc_report_task_result(None, 0, {
                    "task_id": spec.task_id, "results": results,
                    "actor_incarnation": self._actor_incarnation
                    if self.actor_id is not None else None})
            else:
                # batched fast lane: coalesces per owner under load, delivers
                # immediately when idle, requeues on a down owner link
                self.result_buffer.report(spec.owner_address, spec.task_id,
                                          results)
        except Exception:
            logger.warning("could not deliver results of %s to owner %s",
                           spec.method_name, spec.owner_address)
        if t_res:
            # result-deliver stage (the batched lane measures the hand-off
            # into the owner-bound buffer; delivery itself is async)
            tracing.add_complete(
                f"result::{spec.method_name}", "task_result",
                t_res, tracing.now_us() - t_res,
                trace_id=spec.trace_ctx[0], parent_id=spec.trace_ctx[1],
                task_id=spec.task_id.binary().hex(), failed=failed)
        if spec.task_type != TaskType.ACTOR_TASK:
            recycle = False
            if spec.max_calls > 0 and self.mode == "worker":
                # worker recycling (reference max_calls): if this function
                # just hit its budget, retire — the task_done notify tells
                # the raylet to drop us from the pool FIRST so the next
                # task can't be dispatched into the exiting process.
                # Keyed on the FunctionID content hash; a blob-fallback spec
                # (GCS blip during export) hashes to the SAME key, so one
                # function never splits across two counters.
                from ray_tpu.core.ids import FunctionID

                key = spec.function_id or FunctionID.for_blob(
                    spec.function_blob).binary()
                with self._exec_count_lock:
                    self._fn_call_counts[key] = (
                        self._fn_call_counts.get(key, 0) + 1)
                    recycle = self._fn_call_counts[key] >= spec.max_calls
            try:
                self.raylet.notify("task_done", {
                    "worker_id": self.worker_id, "retiring": recycle})
            except OSError as e:
                logger.debug("task_done notify lost (raylet down?): %s", e)
            if recycle:
                logger.info("max_calls=%d reached for %s; recycling worker",
                            spec.max_calls, spec.method_name)
                self.result_buffer.stop()
                self.task_events.flush()
                os._exit(0)

    def _stream_dynamic_returns(self, spec: TaskSpec, value) -> ObjectRefGenerator:
        """Executor side of num_returns="dynamic": iterate the task's
        generator, storing + reporting one object per yielded item (ids
        deterministic in the item index, ids.py for_dynamic_return). Returns
        the completed ObjectRefGenerator used as the task's main return."""
        if not (inspect.isgenerator(value) or hasattr(value, "__next__")):
            # iterATORs only, not iterABLEs: accepting any __iter__ would
            # silently stream a mistakenly-returned str per character or a
            # dict per key (the exact bug this error exists to catch)
            raise TypeError(
                "a num_returns='dynamic' task must return a generator or "
                f"iterator, got {type(value).__name__}")
        item_refs: List[ObjectRef] = []
        for i, item in enumerate(value):
            oid_i = ObjectID.for_dynamic_return(spec.task_id, i)
            self._report_dynamic(spec, self._build_result_entry(oid_i, item))
            item_refs.append(ObjectRef(oid_i, owner_address=spec.owner_address))
        return ObjectRefGenerator(item_refs, done=True)

    def _build_result_entry(self, oid: ObjectID, value) -> tuple:
        """Serialize one return object into a result entry (shared by the
        static return loop and dynamic item streaming): inline below the
        direct-call threshold, plasma above, contained-ref descriptors
        always attached for owner-side container protection."""
        s = serialization.serialize(value)
        self._mark_shipped(s.contained_refs)
        contained = list({(r.id, r.owner_address or self.address)
                          for r in (s.contained_refs or ())})
        if s.total_bytes <= get_config().max_direct_call_object_size:
            return ("inline", oid, s.to_bytes(), contained)
        seg = self._put_to_store(oid, s)
        # the segment name rides the result entry so a CO-LOCATED owner can
        # zero-copy attach its task results without a pull round-trip
        return ("plasma", oid, self.raylet_address, s.total_bytes, contained,
                seg)

    def _deserialize_args(self, args: List[Tuple], kwargs_blob: Optional[bytes]):
        out = []
        for a in args:
            if a[0] == "value":
                out.append(serialization.loads(a[1]))
            else:
                _, oid, owner = a
                ref = ObjectRef(oid, owner_address=owner)
                out.append(self._get_one(ref, deadline=None))
        kwargs = serialization.loads(kwargs_blob) if kwargs_blob else {}
        return out, kwargs
