"""Placement group API.

Mirrors the reference's `python/ray/util/placement_group.py:33,136` with the
four strategies (STRICT_PACK/PACK/SPREAD/STRICT_SPREAD) plus TPU-first
helpers: `tpu_slice_placement_group` reserves an ICI-connected slice worth
of hosts (STRICT_PACK over nodes sharing a `tpu_slice` label) so collectives
compiled over the group's mesh ride ICI.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    name: Optional[str] = None

    def ready(self, timeout: float = 30.0) -> bool:
        from ray_tpu.core.api import _global_worker

        w = _global_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = w.gcs.call("get_placement_group", {"pg_id": self.id})
            if info and info["state"] == "CREATED":
                return True
            if info and info["state"] == "INFEASIBLE":
                # terminal: a replacement head resumed this group's
                # interrupted creation and could not satisfy it — polling
                # longer will never help
                return False
            time.sleep(0.05)
        return False

    def ready_or_raise(self, timeout: float = 30.0) -> "PlacementGroup":
        """`ready()` that surfaces terminal infeasibility as the typed
        `PlacementInfeasibleError` (matched BY TYPE by elastic shrink and
        chaos tests) instead of an indistinguishable False/hang."""
        from ray_tpu.core.api import _global_worker
        from ray_tpu.core.exceptions import PlacementInfeasibleError

        w = _global_worker()
        deadline = time.monotonic() + timeout
        info = None
        while time.monotonic() < deadline:
            info = w.gcs.call("get_placement_group", {"pg_id": self.id})
            if info and info["state"] == "CREATED":
                return self
            if info and info["state"] in ("INFEASIBLE", "PENDING"):
                raise PlacementInfeasibleError(
                    f"placement group {self.id.hex()[:8]} infeasible: "
                    f"{info.get('error', 'no feasible placement')}")
            time.sleep(0.05)
        raise PlacementInfeasibleError(
            f"placement group {self.id.hex()[:8]} not created within "
            f"{timeout}s (state: {info['state'] if info else 'unknown'})")

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def bundle_node_ids(self) -> Optional[List[bytes]]:
        from ray_tpu.core.api import _global_worker

        info = _global_worker().gcs.call("get_placement_group", {"pg_id": self.id})
        return info.get("placement") if info else None


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
) -> PlacementGroup:
    from ray_tpu.core.api import _global_worker

    if strategy not in ("PACK", "STRICT_PACK", "SPREAD", "STRICT_SPREAD"):
        raise ValueError(f"invalid strategy {strategy}")
    w = _global_worker()
    pg_id = PlacementGroupID.from_random()
    w.gcs.call("create_placement_group", {
        "pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name})
    return PlacementGroup(pg_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.api import _global_worker

    _global_worker().gcs.call("remove_placement_group", {"pg_id": pg.id})


def placement_group_table(pg: Optional[PlacementGroup] = None) -> Dict:
    """Debug table of one/all placement groups (reference
    `python/ray/util/placement_group.py:248`)."""
    from ray_tpu.core.api import _global_worker

    w = _global_worker()
    if pg is not None:
        info = w.gcs.call("get_placement_group", {"pg_id": pg.id})
        return {pg.id.hex(): info} if info else {}
    infos = w.gcs.call("list_placement_groups", {}) or []
    return {i["pg_id"].hex() if hasattr(i["pg_id"], "hex") else str(i["pg_id"]): i
            for i in infos}


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The PG the current task/actor is scheduled into, if any (reference
    `python/ray/util/placement_group.py:296`)."""
    from ray_tpu.core.api import get_runtime_context

    try:
        ctx = get_runtime_context()
    except Exception:
        return None
    pg_id = getattr(ctx, "placement_group_id", None)
    if pg_id is None:
        return None
    from ray_tpu.core.api import _global_worker

    info = _global_worker().gcs.call("get_placement_group", {"pg_id": pg_id})
    if not info:
        return None
    return PlacementGroup(pg_id, info.get("bundles", []),
                          info.get("strategy", "PACK"), info.get("name"))


def tpu_slice_placement_group(
    num_hosts: int,
    chips_per_host: Optional[int] = None,
    extra_resources: Optional[Dict[str, float]] = None,
) -> PlacementGroup:
    """Reserve `num_hosts` hosts of one ICI slice (one TPU bundle per host)."""
    from ray_tpu.core.config import get_config

    chips = chips_per_host or get_config().tpu_chips_per_host
    bundle = {"TPU": float(chips), **(extra_resources or {})}
    return placement_group([dict(bundle) for _ in range(num_hosts)], strategy="STRICT_PACK")
