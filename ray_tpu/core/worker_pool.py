"""Warm worker pool: fork-template ("zygote") workers + demand-driven prestart.

Equivalent of the reference's per-runtime-env worker pool with prestart
(`src/ray/raylet/worker_pool.cc:1363` PrestartWorkers, `worker_pool.h:156`),
re-designed around the one cost that dominates this runtime's actor fan-out:
a cold `subprocess.Popen(python -m ray_tpu.core.worker_main)` pays the full
interpreter + `ray_tpu`/numpy import bill per worker, serialized on small
hosts by `maximum_startup_concurrency` (ENVELOPE_r06: 200 actors took 49.2 s
to first ping — almost all of it import CPU).

The subsystem has three parts:

* **Template ("zygote") process** — one per runtime-env key, spawned once
  with the env's interpreter and env vars. It imports `ray_tpu` + the worker
  machinery (and any `RAY_TPU_WORKER_TEMPLATE_PRELOAD` modules), then parks
  single-threaded on a command pipe. Each granted lease costs one
  `os.fork()` (~1 ms) instead of one cold boot (~100-200 ms of import CPU
  that serializes under load): the child closes the template's control fds,
  re-seeds, and runs the exact same `worker_main.run_worker` path a cold
  worker runs — so from registration onward the raylet cannot tell them
  apart except for the stats it keeps.

* **Demand-driven prestart** — the reference policy (~1 worker per CPU up to
  the current backlog) replaces the previously-dead `num_prestart_workers`
  knob, which survives as the FLOOR of the policy: the default env keeps at
  least that many task workers alive (busy, idle or starting) from raylet
  boot onward, and the idle reaper will not shrink the idle pool below the
  floor.

* **Graceful degradation** — anything the fork path cannot serve falls back
  to the cold `Popen` path the raylet has always had: platforms without
  `os.fork`, container runtime envs (`command_prefix` crosses a process
  boundary a host-side fork cannot), runtime envs not yet built (their
  creation runs on the cold path's builder thread), a template that crashed
  or timed out booting. Template crashes respawn under `util/backoff.py`
  full-jitter; while the backoff clock runs, leases are served cold.

Forked workers are adopted into the raylet's existing lifecycle through a
Popen-compatible `ForkedWorkerProc` shim, so idle-kill, `max_calls` recycle,
memory-pressure kills, `recent_done` failover and shutdown treat them
identically to spawned workers.
"""

from __future__ import annotations

import logging
import os
import select
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.util.backoff import ExponentialBackoff

logger = logging.getLogger(__name__)

# Modules a template pre-imports so forked children never pay for them.
# Everything here is import-only (no threads, no sockets, no locks held)
# — the template MUST stay single-threaded or fork() inherits torn state.
_DEFAULT_PRELOAD = (
    "ray_tpu",
    "ray_tpu.core.worker",
    "ray_tpu.core.worker_main",
    "ray_tpu.core.serialization",
    "ray_tpu.core.result_buffer",
    "ray_tpu.core.task_events",
    "numpy",
)


def fork_supported() -> bool:
    return hasattr(os, "fork") and os.name == "posix"


class ForkedWorkerProc:
    """Popen-compatible handle for a worker forked from a template.

    The child's PARENT is the template (which reaps it via SIGCHLD=SIG_IGN),
    so the raylet cannot `waitpid` it — liveness is probed with signal 0 and
    kills are plain `os.kill`. Implements the slice of the Popen surface the
    raylet's lifecycle code touches (`pid`, `poll`, `wait`, `terminate`,
    `kill`, `returncode`) so forked workers ride `_starting`, the reaper,
    idle-kill and `stop()` unchanged.
    """

    forked = True

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        # signal-0 liveness lies once the (template-reaped) pid is reused
        # by an unrelated process: the raylet reaper expires shims still
        # unregistered past worker_register_timeout_s using this stamp
        self.started_at = time.monotonic()

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except OSError:
            # exit status is unknowable from here (the template reaped it)
            self.returncode = -1
            return self.returncode
        # signal 0 succeeds on a ZOMBIE: a child that outlived its template
        # (e.g. shutdown closes the zygote first) reparents to init and
        # lingers unreaped — without this check, wait() spins its full
        # timeout per worker at raylet stop
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                stat = f.read()
            if stat[stat.rfind(b")") + 2:stat.rfind(b")") + 3] == b"Z":
                self.returncode = -1
        except (OSError, IndexError):
            pass  # no /proc (non-Linux): keep the signal-0 answer
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(f"forked:{self.pid}", timeout)
            time.sleep(0.02)
        return self.returncode

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            self.returncode = self.returncode if self.returncode is not None else -1


# --------------------------------------------------------------------------
# template-side (runs inside the zygote process; see worker_main --template)


def template_main(args) -> None:
    """Zygote main loop: preload imports once, then serve fork requests.

    Protocol (newline-delimited, commands on stdin, replies on --reply-fd):
      -> READY <pid>       after preload completes
      FORK ->  OK <pid>    one forked worker (or ERR <msg>)
      PING ->  PONG        liveness probe
      EXIT / stdin EOF     template exits
    The reply channel is a dedicated inherited fd — stdout stays pointed at
    the raylet's console so forked workers print like cold-spawned ones.
    """
    # children are reaped by the kernel; the zygote never waits on them
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)

    preload = list(_DEFAULT_PRELOAD)
    extra = os.environ.get("RAY_TPU_WORKER_TEMPLATE_PRELOAD", "")
    preload += [m.strip() for m in extra.split(",") if m.strip()]
    import importlib

    for mod in preload:
        try:
            importlib.import_module(mod)
        except Exception as e:  # a missing optional preload must not kill
            logger.warning("template preload of %s failed: %s", mod, e)
    if threading.active_count() > 1:
        # fork() from a multi-threaded process duplicates locks mid-flight;
        # nothing in the default preload starts threads, but a user preload
        # might — warn loudly, the forked children may deadlock.
        logger.warning(
            "worker template is multi-threaded after preload (%d threads); "
            "forked workers may inherit torn state",
            threading.active_count())

    reply = os.fdopen(args.reply_fd, "w", buffering=1)
    reply.write(f"READY {os.getpid()}\n")
    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "FORK":
            try:
                pid = os.fork()
            except OSError as e:
                reply.write(f"ERR fork failed: {e}\n")
                continue
            if pid == 0:
                _forked_child_main(args)  # never returns
            reply.write(f"OK {pid}\n")
        elif cmd == "PING":
            reply.write("PONG\n")
        elif cmd == "EXIT":
            break


def _forked_child_main(args) -> None:
    """Runs in the forked child: shed the template's control channel, then
    become a normal worker. Exits via os._exit so the template's inherited
    interpreter state (atexit hooks from preloaded modules) never runs
    twice."""
    code = 0
    try:
        try:
            os.close(args.reply_fd)
        except OSError:
            pass
        # fd 0 is the template's command pipe: a user task reading stdin
        # must see EOF, not steal FORK commands meant for the template
        try:
            devnull = os.open(os.devnull, os.O_RDONLY)
            os.dup2(devnull, 0)
            os.close(devnull)
        except OSError:
            pass
        # the template ignores SIGCHLD so the kernel auto-reaps its forks;
        # a WORKER must not inherit that — user code running subprocesses
        # would get ECHILD from waitpid and read every exit as rc=0
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        import random

        random.seed()  # the template's RNG state is shared by every child
        try:
            import numpy

            # numpy is preloaded in the template: without a reseed every
            # forked worker would draw the SAME 'random' numpy stream
            numpy.random.seed()
        except ImportError:
            pass
        os.environ["RAY_TPU_WORKER_FORKED"] = "1"
        from ray_tpu.core.worker_main import run_worker

        run_worker(args.raylet, args.gcs, log_level=args.log_level)
    except BaseException:
        import traceback

        traceback.print_exc()
        code = 1
    finally:
        os._exit(code)


# --------------------------------------------------------------------------
# raylet-side


class WorkerTemplate:
    """Raylet-side handle to one zygote process."""

    def __init__(self, argv: List[str], env: Dict[str, str]):
        r, w = os.pipe()
        try:
            self.proc = subprocess.Popen(
                argv + ["--reply-fd", str(w)], env=env,
                stdin=subprocess.PIPE, pass_fds=(w,))
        except BaseException:
            os.close(r)
            raise
        finally:
            os.close(w)
        self._reply_fd = r
        self._buf = b""
        self._io_lock = threading.Lock()
        self._close_lock = threading.Lock()
        self._closed = False

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def _readline(self, timeout: float) -> str:
        deadline = time.monotonic() + timeout
        while b"\n" not in self._buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("template reply timed out")
            ready, _, _ = select.select([self._reply_fd], [], [],
                                        min(remaining, 0.5))
            if not ready:
                if not self.alive():
                    raise ConnectionError("template process died")
                continue
            chunk = os.read(self._reply_fd, 4096)
            if not chunk:
                raise ConnectionError("template reply channel closed")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\n")
        return line.decode()

    def wait_ready(self, timeout: float) -> None:
        with self._io_lock:
            line = self._readline(timeout)
        if not line.startswith("READY"):
            raise ConnectionError(f"unexpected template greeting: {line!r}")

    def fork(self, timeout: float) -> int:
        """Request one forked worker; returns its pid. Raises on a dead or
        unresponsive template (callers respawn/fall back cold)."""
        with self._io_lock:
            try:
                self.proc.stdin.write(b"FORK\n")
                self.proc.stdin.flush()
            except (OSError, ValueError) as e:
                raise ConnectionError(f"template stdin closed: {e}") from None
            line = self._readline(timeout)
        if line.startswith("OK "):
            return int(line.split()[1])
        raise ConnectionError(f"template fork failed: {line!r}")

    def close(self) -> None:
        # idempotent + thread-safe: stop() and a fork-failure retire thread
        # can both reach here; a second os.close of the (since recycled)
        # reply fd would close an unrelated live descriptor. The flag rides
        # its OWN lock — _io_lock may be held for up to the boot timeout by
        # a reader waiting on a wedged template, and close() must not wait
        # behind it to terminate that very template.
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # terminate FIRST: a reader blocked in _readline notices the death
        # within its 0.5 s select tick and releases _io_lock
        try:
            self.proc.terminate()
        except OSError:
            pass
        try:
            if self.proc.stdin:
                self.proc.stdin.close()
        except (OSError, ValueError):
            pass
        try:
            self.proc.wait(timeout=2)
        except (OSError, subprocess.TimeoutExpired):
            try:
                self.proc.kill()
            except OSError:
                pass
        with self._io_lock:  # reader gone: the fd is safe to retire
            try:
                os.close(self._reply_fd)
            except OSError:
                pass


@dataclass
class _TemplateSlot:
    """Per-env-key template state (state machine: absent -> booting ->
    ready | failed-with-backoff -> ready ...; cold_only is terminal)."""

    env_key: Optional[str]
    runtime_env: Optional[dict] = None
    handle: Optional[WorkerTemplate] = None
    state: str = "absent"  # absent | booting | ready | failed | cold_only
    backoff: ExponentialBackoff = field(
        default_factory=lambda: ExponentialBackoff(
            base_s=get_config().worker_template_backoff_base_ms / 1000.0,
            cap_s=get_config().worker_template_backoff_cap_ms / 1000.0))
    retry_at: float = 0.0
    last_fork: float = field(default_factory=time.monotonic)
    holds_env_ref: bool = False
    boots: int = 0


class WorkerPool:
    """Per-raylet warm worker pool: owns the templates, the prestart policy
    and the warm/cold accounting; delegates cold spawns back to the raylet's
    original `_spawn_worker` path (which also owns runtime-env creation)."""

    def __init__(self, raylet):
        self._raylet = raylet
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # (env_key, kind) -> [target_demand, runtime_env]; targets are
        # absolute backlog counts (callers re-arm with totals), served by
        # one thread. demand and prestart entries stay SEPARATE per env:
        # their serve-side dedup baselines differ (see _serve)
        self._pending: Dict[Tuple[Optional[str], str], list] = {}
        self._templates: Dict[Optional[str], _TemplateSlot] = {}
        self._shutdown = threading.Event()
        # ---- stats (guarded by _lock) ----
        self.warm_starts = 0        # forks handed to the raylet
        self.cold_starts = 0        # Popen spawns delegated
        self.registered_warm = 0    # forked workers that completed register
        self.registered_cold = 0
        self.template_boots = 0
        self.template_respawns = 0
        self.fork_failures = 0
        self._fork_latencies_ms: deque = deque(maxlen=4096)
        # node-join -> first-warm-lease (the warm-onboarding number): set
        # once, when the FIRST forked worker completes registration
        self.join_to_first_warm_lease_s: Optional[float] = None
        # recent lease traffic per env key (env_key -> [monotonic, renv]);
        # shipped to the GCS in heartbeats so joining nodes prewarm
        self._hot: Dict[Optional[str], list] = {}
        self._thread = threading.Thread(
            target=self._run, name="worker-pool", daemon=True)
        self._thread.start()
        from ray_tpu.util import metrics as _metrics

        self._m_warm = _metrics.get_or_create(
            "counter", "ray_tpu_worker_warm_starts_total",
            "workers started by forking a warm template")
        self._m_cold = _metrics.get_or_create(
            "counter", "ray_tpu_worker_cold_starts_total",
            "workers started by cold Popen spawn")
        self._m_respawn = _metrics.get_or_create(
            "counter", "ray_tpu_worker_template_respawns_total",
            "template crash-respawns")
        self._m_fork_ms = _metrics.get_or_create(
            "histogram", "ray_tpu_worker_fork_latency_ms",
            "FORK request to child-pid reply latency")

    # ------------------------------------------------------------- policy
    def prestart_target(self, backlog: int, env_key: Optional[str]) -> int:
        """Reference prestart policy (~1 per CPU up to the backlog), floored
        by `num_prestart_workers` for the default env."""
        cfg = get_config()
        cpus = int(self._raylet.resources_total.get("CPU", 0)) or (
            os.cpu_count() or 1)
        target = min(max(0, backlog), cpus)
        if env_key is None:
            target = max(target, cfg.num_prestart_workers)
        return target

    def floor(self) -> int:
        """Minimum default-env task-worker population (busy, idle or
        starting) maintained from boot; the reaper's idle-kill also never
        shrinks the idle pool below this."""
        return max(0, get_config().num_prestart_workers)

    # ------------------------------------------------------------ request
    def request(self, env_key: Optional[str], runtime_env: Optional[dict],
                needed: int, kind: str = "demand") -> None:
        """Ask for the env's worker count to reach `needed` (an absolute
        backlog figure). Never blocks: callers hold the raylet lock.

        kind="demand": backed by real queued work. The figure goes stale
        between request and serve (workers register and consume backlog
        in the window), so serve re-reads the live backlog and spawns for
        min(requested, live) — without the re-read a 200-actor burst
        forks ~2x the fleet. kind="prestart": anticipatory (per-submit
        policy, boot floor); deduped against idle AND starting workers.
        """
        if needed <= 0 or self._shutdown.is_set():
            return
        with self._cv:
            # lease-traffic recency per env (warm-onboarding signal)
            hot = self._hot.setdefault(env_key, [0.0, None])
            hot[0] = time.monotonic()
            if runtime_env is not None:
                hot[1] = runtime_env
            entry = self._pending.get((env_key, kind))
            if entry is None:
                self._pending[(env_key, kind)] = [needed, runtime_env]
            else:
                entry[0] = max(entry[0], needed)
                if runtime_env is not None:
                    entry[1] = runtime_env
            self._cv.notify()

    def shed_demand(self) -> int:
        """Job reap: drop every queued spawn figure. The purged backlog may
        have been the demand behind them, and serving a stale figure forks
        workers into a vacuum. Safe for surviving jobs: serve re-reads the
        LIVE backlog before spawning anyway, and every submit/schedule pass
        re-arms its own demand. Returns the number of entries dropped."""
        with self._cv:
            n = len(self._pending)
            self._pending.clear()
        return n

    def prewarm(self, hot_envs) -> None:
        """Warm node onboarding: boot fork templates for the fleet's hot
        runtime-env keys (shipped in the register_node reply) so this
        node's FIRST lease of each hot env is a ~1 ms fork instead of a
        cold multi-second boot. Queued onto the pool serve thread; never
        blocks the caller (the raylet's registration path)."""
        if self._shutdown.is_set():
            return
        for ent in hot_envs or ():
            key = ent.get("env_key")
            with self._cv:
                hot = self._hot.setdefault(key, [0.0, None])
                hot[0] = time.monotonic()
                if ent.get("runtime_env") is not None:
                    hot[1] = ent["runtime_env"]
                if (key, "prewarm") not in self._pending:
                    self._pending[(key, "prewarm")] = \
                        [0, ent.get("runtime_env")]
                self._cv.notify()

    def hot_envs(self, ttl_s: float = 300.0) -> List[Dict]:
        """Env keys with lease traffic in the last `ttl_s` (heartbeat
        payload -> GCS hot-env table -> joiners' prewarm)."""
        now = time.monotonic()
        with self._lock:
            # prune long-cold keys so env churn can't grow the table
            # without bound (heartbeats call this every period)
            for k in [k for k, rec in self._hot.items()
                      if now - rec[0] > max(ttl_s, 3600.0)]:
                del self._hot[k]
            return [{"env_key": k, "runtime_env": rec[1]}
                    for k, rec in self._hot.items()
                    if now - rec[0] <= ttl_s]

    def stats(self) -> Dict:
        with self._lock:
            lat = sorted(self._fork_latencies_ms)
            tmpl = {
                (k if k is not None else ""): {
                    "state": s.state, "boots": s.boots,
                    "pid": s.handle.pid if s.handle else None,
                }
                for k, s in self._templates.items()}
            return {
                "fork_supported": fork_supported(),
                "warm_starts": self.warm_starts,
                "cold_starts": self.cold_starts,
                "registered_warm": self.registered_warm,
                "registered_cold": self.registered_cold,
                "template_boots": self.template_boots,
                "template_respawns": self.template_respawns,
                "fork_failures": self.fork_failures,
                "fork_p50_ms": _pct(lat, 0.50),
                "fork_p99_ms": _pct(lat, 0.99),
                "join_to_first_warm_lease_s": self.join_to_first_warm_lease_s,
                "templates": tmpl,
            }

    def note_registered(self, proc, forked: bool = False) -> None:
        """Raylet callback on worker registration: classify the start. The
        worker's own `forked` payload flag backstops the proc-shim check
        for the adoption race (child registers before the fork reply is
        processed)."""
        warm = forked or bool(getattr(proc, "forked", False))
        first_warm = False
        with self._lock:
            if warm:
                if self.registered_warm == 0 \
                        and self.join_to_first_warm_lease_s is None:
                    joined = getattr(self._raylet, "_joined_at", None)
                    if joined is not None:
                        self.join_to_first_warm_lease_s = round(
                            time.monotonic() - joined, 3)
                        first_warm = True
                self.registered_warm += 1
            else:
                self.registered_cold += 1
        if first_warm:
            # close the node-join -> first-warm-lease measurement at the GCS
            # (off the pool lock; best-effort one-shot)
            try:
                self._raylet.note_first_warm_lease(
                    self.join_to_first_warm_lease_s)
            except Exception:
                logger.debug("first-warm-lease report failed", exc_info=True)

    # ----------------------------------------------------------- lifecycle
    def health_tick(self) -> None:
        """Called from the raylet reaper (~1 Hz): collapse dead templates
        into the failed/backoff state and close idle non-default templates
        (releasing their env ref so runtime-env gc can reclaim the env)."""
        now = time.monotonic()
        cfg = get_config()
        # pass 1, pool lock only: dead templates -> failed; collect idle
        # candidates. NO raylet calls under the pool lock — raylet threads
        # call request() while holding the raylet lock, so a pool-lock ->
        # raylet-lock acquisition here is an ABBA deadlock.
        candidates: List[Tuple[Optional[str], _TemplateSlot]] = []
        with self._lock:
            for key, slot in self._templates.items():
                if slot.state == "ready" and slot.handle is not None \
                        and not slot.handle.alive():
                    logger.warning(
                        "worker template for env %s died (pid %d); backoff "
                        "respawn armed", key or "<default>", slot.handle.pid)
                    self._mark_failed_locked(slot)
                elif (slot.state == "ready" and key is not None
                      and now - slot.last_fork
                      > cfg.worker_template_idle_s):
                    candidates.append((key, slot))
        # pass 2, no pool lock: consult the raylet; pass 3 re-checks the
        # slot under the pool lock before retiring it (a fork may have
        # raced in between)
        idle_keys = [k for k, _ in candidates
                     if not self._raylet._has_workers_for(k)]
        to_close: List[Tuple[_TemplateSlot, WorkerTemplate]] = []
        with self._lock:
            for key, slot in candidates:
                if key in idle_keys and slot.state == "ready" \
                        and slot.handle is not None \
                        and now - slot.last_fork > cfg.worker_template_idle_s:
                    to_close.append((slot, slot.handle))
                    slot.handle = None
                    slot.state = "absent"
        for slot, handle in to_close:
            logger.info("closing idle worker template for env %s",
                        slot.env_key)
            handle.close()
            self._release_env_ref(slot)
        # prestart floor maintenance for the default env (boot + after
        # idle-kill sweeps): keep >= floor workers idle or starting. The
        # request carries (floor - idle) so the serve-side dedup against
        # in-flight starts lands the total exactly at the floor.
        fl = self.floor()
        if fl > 0 and not self._shutdown.is_set():
            if self._raylet._idle_count(None) < fl:
                self.request(None, None, fl, kind="prestart")

    def stop(self) -> None:
        self._shutdown.set()
        with self._cv:
            self._pending.clear()
            slots = list(self._templates.values())
            self._templates.clear()
            self._cv.notify_all()
        for slot in slots:
            if slot.handle is not None:
                slot.handle.close()
            self._release_env_ref(slot)

    def reset_for_fence(self) -> None:
        """Node fencing (partition failure domain): SIGKILL every template
        — their forked children and any state they'd hand out belong to a
        node identity that was declared dead — but keep the pool SERVING:
        the fenced raylet rejoins as a fresh node and must boot templates
        again on demand/prewarm. Unlike kill_all this does NOT shut the
        pool down."""
        with self._cv:
            self._pending.clear()
            slots = list(self._templates.values())
            self._templates.clear()
            self._cv.notify_all()
        for slot in slots:
            handle = slot.handle
            if handle is not None:
                try:
                    handle.proc.kill()
                except OSError:
                    pass
            self._release_env_ref(slot)
        with self._lock:
            # the fresh identity re-measures its own onboarding
            self.join_to_first_warm_lease_s = None

    def kill_all(self) -> None:
        """Whole-node crash simulation: SIGKILL every template outright —
        no EXIT handshake, no graceful close — the way templates die when
        their node dies (chaos harness; see Raylet.crash)."""
        self._shutdown.set()
        with self._cv:
            self._pending.clear()
            slots = list(self._templates.values())
            self._templates.clear()
            self._cv.notify_all()
        for slot in slots:
            handle = slot.handle
            if handle is not None:
                try:
                    handle.proc.kill()
                except OSError:
                    pass

    # ------------------------------------------------------------ internals
    def _release_env_ref(self, slot: _TemplateSlot) -> None:
        # check-and-clear under the pool lock: stop() and a failure-retire
        # thread racing here must release the env ref exactly once
        with self._lock:
            release = slot.holds_env_ref
            slot.holds_env_ref = False
        if release and slot.env_key is not None:
            try:
                self._raylet._env_manager.release(slot.env_key)
            except Exception:
                logger.exception("template env release failed")

    def _mark_failed_locked(self, slot: _TemplateSlot) -> None:
        handle, slot.handle = slot.handle, None
        slot.state = "failed"
        slot.retry_at = time.monotonic() + slot.backoff.next_delay()
        # close + env-ref release off-thread: both do IO (process wait,
        # flock'd refcount file) the pool lock must not be held across.
        # Releasing at failure matters: a failed slot with no further
        # demand is never revisited, and a kept ref would block runtime-env
        # gc of the (possibly huge) env dir for the raylet's lifetime — a
        # respawn re-acquires in _boot_template, and live workers hold
        # their own refs meanwhile.
        release = slot.holds_env_ref
        slot.holds_env_ref = False

        def retire():
            if handle is not None:
                handle.close()
            if release and slot.env_key is not None:
                try:
                    self._raylet._env_manager.release(slot.env_key)
                except Exception:
                    logger.exception("template env release failed")

        threading.Thread(target=retire, daemon=True,
                         name="template-close").start()

    def _run(self) -> None:
        while not self._shutdown.is_set():
            with self._cv:
                while not self._pending and not self._shutdown.is_set():
                    self._cv.wait(timeout=1.0)
                if self._shutdown.is_set():
                    return
                (env_key, kind), (target, runtime_env) = next(
                    iter(self._pending.items()))
                del self._pending[(env_key, kind)]
            try:
                self._serve(env_key, runtime_env, target, kind)
            except Exception:
                logger.exception("worker pool serve failed for env %s",
                                 env_key)

    def _serve(self, env_key: Optional[str], runtime_env: Optional[dict],
               target: int, kind: str = "demand") -> None:
        raylet = self._raylet
        if self._shutdown.is_set() or raylet._shutdown.is_set():
            return
        cfg0 = get_config()
        if kind == "prewarm":
            # onboarding: make the TEMPLATE ready, fork nothing — the first
            # real lease pays ~1 ms instead of a cold boot
            if not cfg0.worker_template_enabled or not fork_supported():
                return
            if env_key is not None:
                if raylet._env_manager.creation_error(env_key) is not None \
                        or not self._env_ready(env_key):
                    return  # env not built on this node: cold path owns it
            slot = self._slot(env_key, runtime_env)
            if slot.state != "absent":
                return
            if env_key is None:
                self._boot_template(slot)
            else:
                # non-default zygotes boot off-thread, same as _serve's
                # demand path: a slow venv boot must not block other envs
                slot.state = "booting"
                threading.Thread(
                    target=self._boot_template, args=(slot,),
                    name="template-prewarm", daemon=True).start()
            return
        if kind == "demand":
            # clamp the (possibly stale) figure to the LIVE backlog before
            # deduping against in-flight starts and idle workers (an idle
            # worker serves a queued task without any spawn)
            target = min(target, raylet._live_demand(env_key))
            deficit = target - raylet._spawn_inflight(env_key) \
                - raylet._idle_count(env_key)
        else:
            # prestart: anticipatory — clamped to the env's OWN live
            # backlog (the per-submit hook passes the global queue depth,
            # which would overspawn for a lightly-loaded env sharing the
            # node), floored for the default env, and deduped against
            # every task-capable worker of the env (busy ones hold their
            # CPU; replacing them with fresh idlers would fork without
            # bound) plus in-flight starts. Dedicated actor workers don't
            # count: they never return to the pool.
            target = min(target, raylet._live_demand(env_key))
            if env_key is None:
                target = max(target, self.floor())
            deficit = target - raylet._task_worker_count(env_key) \
                - raylet._spawn_inflight(env_key)
        if deficit <= 0:
            return
        cfg = get_config()
        if not cfg.worker_template_enabled or not fork_supported():
            self._cold(env_key, runtime_env, deficit)
            return
        if env_key is not None:
            # a not-yet-built env goes through the cold path's builder
            # thread (pip installs can take minutes; this thread must stay
            # responsive for every other env's forks). Once built, later
            # leases come back here and boot the template.
            if raylet._env_manager.creation_error(env_key) is not None:
                return
            if not self._env_ready(env_key):
                self._cold(env_key, runtime_env, deficit)
                return
        slot = self._slot(env_key, runtime_env)
        if slot.state == "cold_only":
            self._cold(env_key, runtime_env, deficit)
            return
        if slot.state == "failed":
            if time.monotonic() < slot.retry_at:
                self._cold(env_key, runtime_env, deficit)
                return
            slot.state = "absent"  # backoff elapsed: try a respawn
        if slot.state == "booting":
            # an async (non-default-env) boot is in flight on its own
            # thread; this round goes cold rather than waiting on it
            self._cold(env_key, runtime_env, deficit)
            return
        if slot.state == "absent":
            if env_key is not None:
                # non-default envs boot OFF the serve thread: a slow pip-env
                # zygote (venv python, cold page cache) must not head-of-
                # line-block every other env's forks for up to the 60 s
                # boot budget. This round is served cold; the next request
                # finds the template ready.
                slot.state = "booting"
                threading.Thread(
                    target=self._boot_template, args=(slot,),
                    name="template-boot", daemon=True).start()
                self._cold(env_key, runtime_env, deficit)
                return
            # The DEFAULT env boots synchronously on purpose: its zygote is
            # plain sys.executable importing in-tree modules (~0.3 s), it
            # is the first thing a fresh cluster needs, and serving the
            # wait-long burst cold would eat the startup-concurrency
            # budget the template exists to retire. Worst case is bounded
            # by worker_template_boot_timeout_s, after which the failed
            # state routes everything cold.
            if not self._boot_template(slot):
                self._cold(env_key, runtime_env, deficit)
                return
        # ready: serve the deficit with forks
        forked = 0
        for _ in range(deficit):
            with self._lock:
                handle = slot.handle  # health_tick may retire it concurrently
            if handle is None:
                # health_tick idle-retired a HEALTHY template between our
                # state check and this fork: that's not a failure — re-queue
                # the remaining work so the next serve round re-boots it
                self.request(env_key, runtime_env, target, kind)
                return
            t0 = time.monotonic()
            try:
                pid = handle.fork(cfg.worker_template_fork_timeout_s)
            except (ConnectionError, TimeoutError, ValueError, OSError) as e:
                logger.warning(
                    "fork from template for env %s failed (%s); cold "
                    "fallback under backoff", env_key or "<default>", e)
                with self._lock:
                    self.fork_failures += 1
                    self._mark_failed_locked(slot)
                # serve the REST of this round's deficit cold: the original
                # figure already carried the idle/task-worker dedup, so the
                # shortfall is exactly what the forks didn't cover
                remaining = deficit - forked
                if remaining > 0:
                    self._cold(env_key, runtime_env, remaining)
                return
            forked += 1
            dt_ms = (time.monotonic() - t0) * 1000.0
            slot.last_fork = time.monotonic()
            raylet._adopt_forked(pid, env_key)
            with self._lock:
                self.warm_starts += 1
                self._fork_latencies_ms.append(dt_ms)
            self._m_warm.inc()
            self._m_fork_ms.observe(dt_ms)

    def _slot(self, env_key: Optional[str],
              runtime_env: Optional[dict]) -> _TemplateSlot:
        with self._lock:
            slot = self._templates.get(env_key)
            if slot is None:
                slot = _TemplateSlot(env_key=env_key, runtime_env=runtime_env)
                self._templates[env_key] = slot
            if runtime_env is not None:
                slot.runtime_env = runtime_env
            return slot

    def _env_ready(self, env_key: str) -> bool:
        base = self._raylet._env_manager.base_dir
        return os.path.exists(os.path.join(base, env_key, ".ready"))

    def _boot_template(self, slot: _TemplateSlot) -> bool:
        """Spawn + await one zygote (blocking; runs on the pool thread)."""
        raylet = self._raylet
        cfg = get_config()
        try:
            python = sys.executable
            ctx_env_vars: Dict[str, str] = {}
            if slot.env_key is not None:
                ctx = raylet._env_manager.context_for(slot.runtime_env or {})
                if ctx.command_prefix:
                    # container envs wrap the worker argv in an engine CLI:
                    # a host-side fork can't cross that boundary
                    slot.state = "cold_only"
                    return False
                python = ctx.python
                ctx_env_vars = dict(ctx.env_vars)
                if not slot.holds_env_ref:
                    raylet._env_manager.acquire(slot.env_key)
                    slot.holds_env_ref = True
            env = raylet._build_worker_env(slot.env_key)
            env.update(ctx_env_vars)
            argv = [python, "-m", "ray_tpu.core.worker_main", "--template",
                    "--raylet", raylet.address, "--gcs", raylet.gcs_address,
                    "--node-id", raylet.node_id.hex()]
            slot.state = "booting"
            respawn = slot.boots > 0
            handle = WorkerTemplate(argv, env)
            # visible on the slot immediately so a failed boot (timeout,
            # crash) is closed by _mark_failed_locked, never leaked
            slot.handle = handle
            handle.wait_ready(cfg.worker_template_boot_timeout_s)
            slot.state = "ready"
            # a stale pre-close stamp would let health_tick idle-retire a
            # just-booted template before it serves its first fork
            slot.last_fork = time.monotonic()
            slot.backoff.reset()
            slot.boots += 1
            with self._lock:
                self.template_boots += 1
                if respawn:
                    self.template_respawns += 1
            if respawn:
                self._m_respawn.inc()
            logger.info("worker template for env %s ready (pid %d)",
                        slot.env_key or "<default>", handle.pid)
            return True
        except Exception as e:
            logger.warning(
                "worker template boot for env %s failed (%s); cold fallback "
                "under backoff", slot.env_key or "<default>", e)
            with self._lock:
                self._mark_failed_locked(slot)
            return False

    def _cold(self, env_key: Optional[str], runtime_env: Optional[dict],
              deficit: int) -> None:
        """Cold Popen fallback, bounded by the classic startup-concurrency
        budget (multi-second boots must not all serialize at once)."""
        raylet = self._raylet
        if self._shutdown.is_set() or raylet._shutdown.is_set():
            return
        budget = get_config().maximum_startup_concurrency \
            - raylet._starting_count()
        n = max(0, min(deficit, budget))
        if n <= 0:
            return
        # count only spawns that actually happened: for a still-creating
        # venv env every call but the first is suppressed, and counting
        # them would inflate cold_starts (skewing warm_start_fraction)
        spawned = sum(1 for _ in range(n)
                      if raylet._spawn_worker(env_key, runtime_env))
        if spawned:
            with self._lock:
                self.cold_starts += spawned
            self._m_cold.inc(spawned)


def _pct(sorted_vals, q: float) -> Optional[float]:
    from ray_tpu.util.stats import percentile

    v = percentile(sorted_vals, q)
    return None if v is None else round(v, 3)
