"""Store storm: the storage failure domain under compound fire.

Drives a single-node cluster's object store at a multiple of its shm
capacity so spilling is the steady state, then layers every storage
failure mode on top — seeded through the `fs:<site>` fault points the
store itself exercises (`core/object_store.py`):

  * ENOSPC windows: every spill dir refuses writes
    (``fs:spill_write:enospc``) — the store must walk its retry ladder,
    enter SPILL-DEGRADED, flip puts to typed ``ObjectStoreFullError``
    backpressure, and SELF-HEAL through its probe once the window lifts;
  * spill corruption: seeded bitflip/torn envelopes at spill-write time
    and EIO at restore time — a later read must detect the damage via
    the checksummed envelope (never return corrupt bytes), mark the copy
    LOST, and route task-produced objects into lineage reconstruction;
  * long-held reader pins past ``max_pinned_fraction`` — further readers
    must degrade to bounded copy-only grants (``pin_cap``), not wedge
    the store and not report objects lost;
  * memory-monitor OOM kills of producer workers mid-storm
    (deterministic ``memory_monitor_worker_budget_bytes`` mode) —
    retriable producers complete, a no-retry hog surfaces a typed
    ``OutOfMemoryError``.

The storm asserts the storage contract:

  * ZERO hung gets — every get resolves within its budget as a value,
    a reconstructed value, or a TYPED error;
  * ZERO silent corruption — every resolved value's crc32 matches the
    payload recomputed from (seed, index): a bitflipped spill that
    round-trips unnoticed fails the run;
  * typed backpressure — puts during the degraded window fail with
    ``ObjectStoreFullError``, nothing else;
  * post-heal convergence — after the chaos lifts and refs drop, the
    store exits degraded state, sheds its pins, and settles back under
    the spill threshold.

Writes a JSON artifact (STORESTORM_r18.json). Run directly:

    python -m ray_tpu.core.memstorm             # full profile
    python -m ray_tpu.core.memstorm --quick     # CI profile
"""

from __future__ import annotations

import argparse
import gc
import json
import logging
import os
import shutil
import sys
import tempfile
import time
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class MemStormProfile:
    capacity_mb: int = 128        # object store shm budget
    object_mb: int = 3            # per-object payload
    overcommit: float = 3.0       # live object bytes vs capacity
    wave: int = 8                 # concurrent producer tasks per wave
    corrupt_prob: float = 0.35    # bitflip prob during the corrupt window
    restore_eio_prob: float = 0.3  # EIO prob during the restore window
    restore_eio_gets: int = 24    # gets swept inside the restore window
    degrade_cycles: int = 2       # ENOSPC -> degraded -> heal cycles
    max_pinned_fraction: float = 0.35
    held_pins: int = 18           # held readers (held bytes > pin cap)
    oom_hogs: int = 8             # retriable hogs (4 concurrent ~2x budget)
    hog_mb: int = 260
    oom_budget_mb: int = 700      # memory_monitor_worker_budget_bytes
    seed: int = 0
    put_full_timeout_s: float = 1.5
    get_timeout_s: float = 60.0
    settle_timeout_s: float = 90.0


QUICK_PROFILE = dict(capacity_mb=64, object_mb=2, overcommit=2.5,
                     wave=6, restore_eio_gets=12, degrade_cycles=1,
                     held_pins=14, oom_hogs=4, hog_mb=150,
                     oom_budget_mb=400, settle_timeout_s=60.0)


def _payload(seed: int, i: int, nbytes: int):
    """Deterministic position-dependent payload for (seed, i): the
    consumer recomputes it to verify end-to-end integrity, so a spill
    bitflip that survives the envelope check cannot go unnoticed."""
    import numpy as np

    base = np.arange(nbytes, dtype=np.uint64)
    return ((base * 2654435761 + seed * 1000003 + i) & 0xFF).astype(
        np.uint8)


def _crc(seed: int, i: int, nbytes: int) -> int:
    return zlib.crc32(_payload(seed, i, nbytes))


def run_memstorm(profile: Optional[MemStormProfile] = None,
                 out_path: Optional[str] = None) -> Dict[str, Any]:
    """One store storm on a fresh single-node in-process cluster (the
    raylet + store run in THIS process, so the installed fault injector
    reaches the spill fault points). The caller must NOT have ray_tpu
    initialized."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core import rpc
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config
    from ray_tpu.core.exceptions import (ObjectLostError,
                                         ObjectStoreFullError,
                                         OutOfMemoryError)

    p = profile or MemStormProfile()
    capacity = p.capacity_mb << 20
    nbytes = p.object_mb << 20
    cfg = get_config()
    saved = (cfg.object_spill_dirs, cfg.spill_degraded_probe_period_s,
             cfg.put_full_timeout_s, cfg.max_pinned_fraction,
             cfg.memory_monitor_worker_budget_bytes,
             cfg.memory_usage_threshold, cfg.memory_monitor_refresh_ms,
             cfg.memory_monitor_kill_cooldown_ms)
    extra_spill_root = tempfile.mkdtemp(prefix="rtpu-memstorm-spill-")
    cfg.object_spill_dirs = extra_spill_root
    cfg.spill_degraded_probe_period_s = 0.3
    cfg.put_full_timeout_s = p.put_full_timeout_s
    cfg.max_pinned_fraction = p.max_pinned_fraction
    cfg.memory_monitor_worker_budget_bytes = p.oom_budget_mb << 20
    cfg.memory_usage_threshold = 0.9
    cfg.memory_monitor_refresh_ms = 100
    cfg.memory_monitor_kill_cooldown_ms = 500

    violations: List[str] = []
    phases: Dict[str, Any] = {}
    inj = rpc.install_fault_injector("", seed=p.seed)
    cluster = None
    raylet = None
    try:
        cluster = Cluster()
        raylet = cluster.add_node(num_cpus=4,
                                  object_store_memory=capacity)
        cluster.connect()
        store = raylet.store
        threshold = cfg.object_spilling_threshold

        @ray_tpu.remote(max_retries=4)
        def produce(seed, i, nbytes):
            return _payload(seed, i, nbytes)

        @ray_tpu.remote(max_retries=6)
        def hog(i, ballast_mb):
            import numpy as _np
            import time as _t

            ballast = _np.ones((ballast_mb << 20) // 8)
            _t.sleep(1.0)
            return i + int(ballast[0])

        @ray_tpu.remote(max_retries=0)
        def uber_hog(ballast_mb):
            import numpy as _np
            import time as _t

            ballast = _np.ones((ballast_mb << 20) // 8)
            _t.sleep(30.0)
            return int(ballast[0])

        task_refs: List[Any] = []   # (ref, i) — lineage-recoverable
        put_refs: List[Any] = []    # (ref, i) — no lineage (driver puts)

        def produce_wave(start: int, count: int) -> None:
            idx = list(range(start, start + count))
            for chunk in range(0, len(idx), p.wave):
                batch = idx[chunk:chunk + p.wave]
                refs = [produce.remote(p.seed, i, nbytes) for i in batch]
                # resolve the wave so production can't outrun the store
                ray_tpu.get(refs, timeout=p.get_timeout_s)
                task_refs.extend(zip(refs, batch))

        # ---- phase 1: fill to overcommit (spilling = steady state) ------
        t0 = time.monotonic()
        n_fill = max(p.wave, int(p.overcommit * capacity / nbytes))
        produce_wave(0, n_fill)
        st = store.stats()
        if st["spilled_bytes_total"] == 0:
            violations.append(
                f"fill never spilled: {st['used_bytes']}B used of "
                f"{capacity}B with {n_fill} x {nbytes}B live objects")
        phases["fill"] = {
            "objects": n_fill, "s": round(time.monotonic() - t0, 2),
            "spilled_bytes_total": st["spilled_bytes_total"]}

        # ---- restore bandwidth: gets over the cold (spilled) tail -------
        # the oldest fill objects were evicted first; reading them back
        # measures the verified-restore path (envelope check included)
        restored0 = st["restored_bytes_total"]
        t0 = time.monotonic()
        for ref, i in task_refs[:p.wave]:
            arr = ray_tpu.get(ref, timeout=p.get_timeout_s)
            del arr
        restore_s = time.monotonic() - t0
        restored_delta = (store.stats()["restored_bytes_total"]
                          - restored0)
        spill_restore_gbps = (
            round(restored_delta / restore_s / 1e9, 3)
            if restored_delta and restore_s > 0 else None)
        phases["restore_bandwidth"] = {
            "restored_bytes": restored_delta,
            "s": round(restore_s, 3),
            "spill_restore_gbps": spill_restore_gbps}

        # ---- phase 2: corrupt window (bitflip + torn spill envelopes) ---
        t0 = time.monotonic()
        r_bitflip = inj.fs("spill_write", "bitflip", prob=p.corrupt_prob)
        r_torn = inj.fs("spill_write", "torn", prob=p.corrupt_prob / 2)
        n_extra = max(p.wave, int(0.5 * capacity / nbytes))
        produce_wave(n_fill, n_extra)
        r_bitflip.armed = False
        r_torn.armed = False
        phases["corrupt_window"] = {
            "objects": n_extra, "s": round(time.monotonic() - t0, 2)}

        # ---- phase 3: ENOSPC -> degraded -> typed backpressure -> heal --
        cycles = []
        puts_rejected_typed = 0
        for cyc in range(p.degrade_cycles):
            t0 = time.monotonic()
            r_enospc = inj.fs("spill_write", "enospc", prob=1.0)
            typed = untyped = 0
            # drive puts into the window: the ladder fails every dir,
            # the store degrades, and puts flip to bounded typed errors
            for k in range(64):
                i = 100_000 + cyc * 1000 + k
                try:
                    put_refs.append((ray_tpu.put(_payload(p.seed, i,
                                                          nbytes)), i))
                except ObjectStoreFullError:
                    typed += 1
                    if typed >= 2:
                        break
                except Exception as e:
                    untyped += 1
                    violations.append(
                        f"degraded put raised untyped "
                        f"{type(e).__name__}: {e}"[:160])
                    break
            if typed == 0:
                violations.append(
                    f"cycle {cyc}: ENOSPC window never produced a typed "
                    f"ObjectStoreFullError put rejection")
            puts_rejected_typed += typed
            if not store.stats()["spill_degraded"]:
                violations.append(
                    f"cycle {cyc}: store never entered spill-degraded "
                    f"state under all-dirs ENOSPC")
            t_degraded = time.monotonic()
            r_enospc.armed = False
            # self-heal: the probe runs on allocation pressure; small
            # puts tick it until the store exits degraded state
            healed = False
            heal_deadline = time.monotonic() + p.settle_timeout_s
            while time.monotonic() < heal_deadline:
                try:
                    i = 200_000 + cyc * 1000 + int(
                        (time.monotonic() - t_degraded) * 100)
                    put_refs.append((ray_tpu.put(_payload(p.seed, i,
                                                          nbytes)), i))
                except ObjectStoreFullError:
                    pass  # still degraded/full: keep ticking the probe
                if not store.stats()["spill_degraded"]:
                    healed = True
                    break
                time.sleep(0.1)
            if not healed:
                violations.append(
                    f"cycle {cyc}: store never healed after the ENOSPC "
                    f"window lifted")
            cycles.append({
                "typed_put_rejections": typed,
                "heal_s": round(time.monotonic() - t_degraded, 2)
                if healed else None,
                "s": round(time.monotonic() - t0, 2)})
        phases["degrade_cycles"] = cycles

        # ---- phase 4: long-held reader pins past the cap ----------------
        t0 = time.monotonic()
        pin_cap0 = store.stats()["pin_cap_refusals"]
        held = []
        rng_idx = [(i * 7919) % len(task_refs)
                   for i in range(p.held_pins)]
        for j in sorted(set(rng_idx))[:p.held_pins]:
            ref, i = task_refs[j]
            arr = ray_tpu.get(ref, timeout=p.get_timeout_s)
            if zlib.crc32(np.ascontiguousarray(arr)) != _crc(p.seed, i,
                                                             nbytes):
                violations.append(
                    f"held-pin get of object {i} returned corrupt bytes")
            held.append(arr)
        held_bytes = sum(a.nbytes for a in held)
        # with the cap exceeded, further reads must still resolve —
        # served as bounded copy-only grants, not wedges or false losses
        extra_ok = 0
        for j in range(p.held_pins, p.held_pins + 6):
            ref, i = task_refs[(j * 104729) % len(task_refs)]
            arr = ray_tpu.get(ref, timeout=p.get_timeout_s)
            if zlib.crc32(np.ascontiguousarray(arr)) == _crc(p.seed, i,
                                                             nbytes):
                extra_ok += 1
            del arr
        pin_cap_refusals = store.stats()["pin_cap_refusals"] - pin_cap0
        if held_bytes > p.max_pinned_fraction * capacity \
                and pin_cap_refusals == 0:
            violations.append(
                f"{held_bytes}B held past the "
                f"{p.max_pinned_fraction:.2f} cap but pin_cap_refusals "
                f"never fired")
        phases["pin_pressure"] = {
            "held": len(held), "held_bytes": held_bytes,
            "reads_past_cap_ok": extra_ok,
            "pin_cap_refusals": pin_cap_refusals,
            "s": round(time.monotonic() - t0, 2)}
        del held
        gc.collect()

        # ---- phase 5: memory-monitor OOM kills of producers -------------
        t0 = time.monotonic()
        kills0 = raylet.oom_kills_total
        hog_refs = [hog.remote(i, p.hog_mb) for i in range(p.oom_hogs)]
        hogs_ok = 0
        for i, r in enumerate(hog_refs):
            try:
                if ray_tpu.get(r, timeout=p.settle_timeout_s * 2) == i + 1:
                    hogs_ok += 1
                else:
                    violations.append(f"hog {i} returned a wrong value")
            except Exception as e:
                violations.append(
                    f"retriable hog {i} never completed: "
                    f"{type(e).__name__}")
        typed_oom = False
        try:
            ray_tpu.get(uber_hog.remote(int(p.oom_budget_mb * 1.3)),
                        timeout=p.settle_timeout_s * 2)
            violations.append("uber-hog exceeding the budget succeeded")
        except OutOfMemoryError:
            typed_oom = True
        except Exception as e:
            violations.append(
                f"uber-hog died untyped: {type(e).__name__}: {e}"[:160])
        oom_kills = raylet.oom_kills_total - kills0
        if oom_kills == 0:
            violations.append("memory monitor never killed a worker "
                              "under 2x budget oversubscription")
        phases["oom"] = {
            "hogs_completed": hogs_ok, "of": p.oom_hogs,
            "oom_kills": oom_kills, "typed_oom_error": typed_oom,
            "s": round(time.monotonic() - t0, 2)}

        # ---- phase 6: resolution sweep (zero hung, zero corruption) -----
        t0 = time.monotonic()
        outcomes = {"verified": 0, "typed_lost": 0, "hung": 0,
                    "crc_mismatch": 0, "untyped": 0}
        restore_window = min(p.restore_eio_gets, len(task_refs))
        r_eio = inj.fs("spill_restore", "eio", prob=p.restore_eio_prob)
        deadline = time.monotonic() + p.settle_timeout_s * 2
        for n, (ref, i) in enumerate(task_refs + put_refs):
            if n == restore_window:
                r_eio.armed = False
            is_put = n >= len(task_refs)
            per_get = min(p.get_timeout_s,
                          max(1.0, deadline - time.monotonic()))
            try:
                arr = ray_tpu.get(ref, timeout=per_get)
            except ObjectLostError:
                if is_put:
                    # driver puts have no lineage: a lost spilled copy
                    # legitimately resolves as a typed loss
                    outcomes["typed_lost"] += 1
                else:
                    outcomes["untyped"] += 1
                    violations.append(
                        f"task object {i} lost despite lineage "
                        f"(reconstruction failed)")
                continue
            except ray_tpu.GetTimeoutError:
                outcomes["hung"] += 1
                violations.append(f"get of object {i} hung past "
                                  f"{per_get:.0f}s")
                continue
            except Exception as e:
                outcomes["untyped"] += 1
                violations.append(
                    f"get of object {i} raised "
                    f"{type(e).__name__}: {e}"[:160])
                continue
            if zlib.crc32(np.ascontiguousarray(arr)) == _crc(p.seed, i,
                                                             nbytes):
                outcomes["verified"] += 1
            else:
                outcomes["crc_mismatch"] += 1
                violations.append(
                    f"SILENT CORRUPTION: object {i} resolved with a "
                    f"wrong checksum")
            del arr
        r_eio.armed = False
        phases["sweep"] = dict(outcomes,
                               total=len(task_refs) + len(put_refs),
                               s=round(time.monotonic() - t0, 2))

        # ---- phase 7: post-heal convergence -----------------------------
        t0 = time.monotonic()
        task_refs.clear()
        put_refs.clear()
        gc.collect()
        converged = False
        conv_deadline = time.monotonic() + p.settle_timeout_s
        while time.monotonic() < conv_deadline:
            st = store.stats()
            if not st["spill_degraded"] and st["pinned_bytes"] == 0 \
                    and st["used_bytes"] <= threshold * capacity:
                converged = True
                break
            gc.collect()
            time.sleep(0.2)
        st = store.stats()
        if not converged:
            violations.append(
                f"store never converged post-heal: used="
                f"{st['used_bytes']}B (threshold "
                f"{int(threshold * capacity)}B) pinned="
                f"{st['pinned_bytes']}B degraded="
                f"{st['spill_degraded']}")
        phases["convergence"] = {
            "converged": converged,
            "used_fraction": round(st["used_bytes"] / capacity, 3),
            "s": round(time.monotonic() - t0, 2)}

        result = {
            "suite": "store storm (storage failure domain)",
            "profile": {
                "capacity_mb": p.capacity_mb, "object_mb": p.object_mb,
                "overcommit": p.overcommit,
                "corrupt_prob": p.corrupt_prob,
                "restore_eio_prob": p.restore_eio_prob,
                "degrade_cycles": p.degrade_cycles,
                "max_pinned_fraction": p.max_pinned_fraction,
                "held_pins": p.held_pins, "oom_hogs": p.oom_hogs,
                "oom_budget_mb": p.oom_budget_mb, "seed": p.seed,
            },
            "phases": phases,
            "counters": {
                "spilled_bytes_total": st["spilled_bytes_total"],
                "restored_bytes_total": st["restored_bytes_total"],
                "spill_failures": st["spill_failures"],
                "lost_spills": st["lost_spills"],
                "put_backpressure": st["put_backpressure"],
                "pin_cap_refusals": st["pin_cap_refusals"],
                "degraded_enters": st["degraded_enters"],
                "degraded_heals": st["degraded_heals"],
                "puts_rejected_typed": puts_rejected_typed,
                "fs_faults_injected": inj.stats["fs"],
            },
            "spill_restore_gbps": spill_restore_gbps,
            "zero_hung": phases["sweep"]["hung"] == 0,
            "zero_silent_corruption":
                phases["sweep"]["crc_mismatch"] == 0,
            "violations": violations,
            "ok": not violations,
        }
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        return result
    finally:
        rpc.clear_fault_injector()
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("memstorm cluster shutdown failed")
        shutil.rmtree(extra_spill_root, ignore_errors=True)
        (cfg.object_spill_dirs, cfg.spill_degraded_probe_period_s,
         cfg.put_full_timeout_s, cfg.max_pinned_fraction,
         cfg.memory_monitor_worker_budget_bytes,
         cfg.memory_usage_threshold, cfg.memory_monitor_refresh_ms,
         cfg.memory_monitor_kill_cooldown_ms) = saved


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.WARNING)
    ap = argparse.ArgumentParser(
        description="store storm: the storage failure domain under fire")
    ap.add_argument("--quick", action="store_true",
                    help="small CI profile")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None,
                    help="write the result artifact here")
    args = ap.parse_args(argv)
    kw: Dict[str, Any] = dict(QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    p = MemStormProfile(**kw)
    result = run_memstorm(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    c = result["counters"]
    sw = result["phases"]["sweep"]
    print(f"[memstorm] seed={p.seed} capacity={p.capacity_mb}MB "
          f"overcommit={p.overcommit}x | gets={sw['total']} "
          f"verified={sw['verified']} typed_lost={sw['typed_lost']} "
          f"hung={sw['hung']} crc_mismatch={sw['crc_mismatch']} | "
          f"spilled={c['spilled_bytes_total']} "
          f"restored={c['restored_bytes_total']} "
          f"spill_failures={c['spill_failures']} "
          f"lost_spills={c['lost_spills']} | "
          f"backpressure={c['put_backpressure']} "
          f"pin_cap={c['pin_cap_refusals']} "
          f"degraded={c['degraded_enters']}/"
          f"heals={c['degraded_heals']} "
          f"oom_kills={result['phases']['oom']['oom_kills']}",
          file=sys.stderr)
    if not result["ok"]:
        print("[memstorm] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
