"""Unique identifiers for jobs, tasks, objects, actors, nodes and workers.

Equivalent role to the reference's `src/ray/common/id.h` (JobID/TaskID/
ObjectID/ActorID/NodeID byte-string ids with embedded structure). We keep the
same structural idea — ObjectIDs embed the creating TaskID plus a return/put
index so ownership and lineage can be derived from the id itself — but the
representation is a plain bytes-backed value type; there is no need for the
reference's C++ bit-packing.
"""

from __future__ import annotations

import os
import threading

_UNIQUE_LEN = 16  # bytes of entropy for "root" ids


class BaseID:
    """A bytes-backed, hashable, comparable unique id."""

    __slots__ = ("_bytes",)
    _NIL: "BaseID | None" = None

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes):
            raise TypeError(f"{type(self).__name__} requires bytes, got {type(id_bytes)}")
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_UNIQUE_LEN))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _UNIQUE_LEN)

    def is_nil(self) -> bool:
        return all(b == 0 for b in self._bytes)

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    __slots__ = ()


class NodeID(BaseID):
    __slots__ = ()


class WorkerID(BaseID):
    __slots__ = ()


class ActorID(BaseID):
    __slots__ = ()


class PlacementGroupID(BaseID):
    __slots__ = ()


class TaskID(BaseID):
    __slots__ = ()


class FunctionID(BaseID):
    """Content hash of an exported function/class pickle (reference
    `python/ray/_private/function_manager.py` function ids): the same blob
    always maps to the same id, so the export-once function table is
    content-addressed — re-decorating an identical function dedupes to one
    GCS entry."""

    __slots__ = ()

    @classmethod
    def for_blob(cls, blob: bytes) -> "FunctionID":
        import hashlib

        return cls(hashlib.blake2b(blob, digest_size=_UNIQUE_LEN).digest())


class ObjectID(BaseID):
    """ObjectID = TaskID bytes + 4-byte big-endian index.

    Index semantics (cf. reference ObjectID::ForTaskReturn / FromIndex):
      - return values of a task use indices 1..n
      - `put` objects use indices starting at PUT_INDEX_BASE
    """

    __slots__ = ()
    PUT_INDEX_BASE = 1 << 24
    DYNAMIC_INDEX_BASE = 1 << 16  # dynamic (generator) returns, < PUT base

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls.for_task_return(task_id, cls.PUT_INDEX_BASE + put_index)

    @classmethod
    def for_dynamic_return(cls, task_id: TaskID, item_index: int) -> "ObjectID":
        """Id of the item_index-th object streamed out of a generator task
        (num_returns='dynamic'). Deterministic in (task, index) so a
        re-executed generator regenerates the SAME ids — lineage
        reconstruction of dynamically-created objects falls out for free
        (cf. reference ObjectID::FromIndex use in _raylet.pyx:997)."""
        return cls.for_task_return(task_id, cls.DYNAMIC_INDEX_BASE + item_index)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:-4])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[-4:], "big")


class _TaskIDCounter:
    """Per-worker deterministic task id generation: parent task id + counter.

    Mirrors the reference's TaskID::ForNormalTask(job, parent, counter) so ids
    are reproducible for lineage reconstruction.
    """

    def __init__(self, worker_id: WorkerID):
        self._worker_id = worker_id
        self._count = 0
        self._lock = threading.Lock()

    def next_task_id(self) -> TaskID:
        with self._lock:
            self._count += 1
            c = self._count
        # Derive from worker id + counter; hash to fixed width.
        import hashlib

        h = hashlib.blake2b(
            self._worker_id.binary() + c.to_bytes(8, "big"), digest_size=_UNIQUE_LEN
        )
        return TaskID(h.digest())
