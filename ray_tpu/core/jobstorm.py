"""Job storm: the job failure domain under fire.

N concurrent driver *processes* (separate OS processes joining one cluster)
each run nested task trees, named + detached actors, and large plasma puts.
A seeded subset is SIGKILLed mid-flight — the exact failure the driver-death
fate-sharing path (gcs.py `_on_driver_conn_close` -> `_reap_job`) exists for.
The harness then asserts the blast radius is exactly one job wide:

  - every killed job is marked DEAD and fully reaped (workers killed, queued
    tasks cancelled, primary object copies dropped, function exports freed)
    within `reap_bound_s` of the SIGKILL;
  - detached actors owned by the corpses survive and answer a *fresh* driver
    process by name, with their pre-kill state intact;
  - cross-job `get()` of a reaped job's object raises the typed
    `OwnerDiedError` — never a hang, never a bare socket error;
  - surviving drivers keep making progress: their task throughput during the
    kill window stays above a CPU-calibrated fraction of their pre-storm
    baseline, and every one of them drains CLEAN (exit 0, no hung get);
  - nothing leaks: no worker process, queued task, or object-table entry
    still attributed to a dead job after the reap settles, and no /dev/shm
    segment of any store survives cluster shutdown.

Run `python -m ray_tpu.core.jobstorm --quick` for the CI profile; the full
profile writes the committed `JOBSTORM_r20.json` artifact.  The same module
doubles as the victim / verifier driver entrypoint (`--victim`, `--verify`)
so the remote functions live in an importable module, not a `-c` __main__.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core import rpc
from ray_tpu.core.cluster import Cluster
from ray_tpu.core.exceptions import OwnerDiedError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


# ----------------------------------------------------------------- workload

@ray_tpu.remote
def _storm_leaf(x):
    return x + 1


# Near-zero CPU demand: tree parents BLOCK in get() while their children run,
# and a blocked parent does not release its CPU grant — at full fanout the
# inner nodes would deadlock the cluster if each held a whole core.
@ray_tpu.remote(num_cpus=0.05)
def _storm_tree(depth, fanout):
    """Nested task tree; returns the node count of the subtree."""
    if depth <= 0:
        return 1
    refs = [_storm_tree.remote(depth - 1, fanout) for _ in range(fanout)]
    return 1 + sum(ray_tpu.get(refs, timeout=120.0))


@ray_tpu.remote
class StormCounter:
    def __init__(self):
        self._n = 0

    def bump(self):
        self._n += 1
        return self._n

    def value(self):
        return self._n


# ------------------------------------------------------------------ profile

@dataclass
class JobStormProfile:
    n_jobs: int = 6            # concurrent driver processes
    n_kill: int = 3            # SIGKILLed mid-flight (seeded choice)
    detached_every: int = 2    # every k-th driver also owns a detached actor
    driver_duration_s: float = 22.0
    baseline_s: float = 4.0    # pre-kill throughput measurement window
    kill_gap_s: float = 1.2    # stagger between SIGKILLs
    tick_sleep_s: float = 0.15
    fanout: int = 2
    tree_depth: int = 2
    put_mb: float = 4.0        # large plasma put pinned by each driver
    reap_bound_s: float = 6.0  # SIGKILL -> job DEAD + reaped
    get_timeout_s: float = 60.0   # every driver-side get is bounded by this
    drain_grace_s: float = 30.0
    seed: int = 0


QUICK_PROFILE: Dict[str, Any] = dict(
    n_jobs=4, n_kill=2, driver_duration_s=14.0, baseline_s=3.0,
    kill_gap_s=1.0, tree_depth=1, put_mb=1.0, drain_grace_s=25.0,
)


def full_profile_kwargs() -> Dict[str, Any]:
    """Machine calibration for the FULL profile (the quick CI profile is
    light enough to hold its defaults everywhere): the storm's job count
    and bounds assume ~8 effective CPUs. On smaller boxes only the
    TIMEOUTS stretch — the load stays, the patience grows — so pure
    timesharing (6 driver processes + cluster + workers on one core)
    doesn't convert slow ticks into false hung-call violations. The
    drain grace must cover a worst-case final-tick get, so it tracks
    the stretched get timeout."""
    try:
        n = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        n = os.cpu_count() or 1
    kw: Dict[str, Any] = {}
    if n < 8:
        f = 8.0 / max(1, n)
        kw["get_timeout_s"] = min(240.0, 60.0 * f)
        kw["reap_bound_s"] = min(15.0, 6.0 * f)
        kw["tick_sleep_s"] = 0.25
        kw["drain_grace_s"] = kw["get_timeout_s"] + 30.0
    return kw


def throughput_floor_frac() -> float:
    """Survivor throughput floor during the storm, as a fraction of the
    pre-kill baseline — machine-calibrated like serve.storm's
    error_spike_bound(): 0.25 at >= 8 effective CPUs, linearly relaxed
    to 0.05 on a single-core box where driver respawn churn alone can
    eat most of the machine."""
    try:
        n = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        n = os.cpu_count() or 1
    if n >= 8:
        return 0.25
    return max(0.05, 0.25 * n / 8.0)


# ---------------------------------------------------- victim / verifier CLI

def run_victim(args) -> int:
    """One storm driver: register, create a named (+ optionally detached)
    counter actor, pin a large put, then tick task trees until the duration
    elapses — or until the host SIGKILLs us mid-tick.  Every get is bounded
    by --get-timeout so a hang is a *detected* failure, not a stuck CI job.
    Protocol lines on stdout (flush=True): JOB / DET / PUT / VICTIM_READY /
    TICK <n> <completed> / CLEAN <completed> / DRIVER_ERROR <msg>."""
    try:
        ray_tpu.init(address=args.address)
        from ray_tpu.core.api import _global_worker
        w = _global_worker()
        print(f"JOB {w.job_id.hex()}", flush=True)
        to = args.get_timeout
        cnt = StormCounter.options(name=f"storm-cnt-{args.index}").remote()
        det = None
        if args.detached:
            det = StormCounter.options(
                name=f"storm-det-{args.index}", lifetime="detached").remote()
            # Pre-kill state the post-mortem verifier asserts on.
            ray_tpu.get(det.bump.remote(), timeout=to)
            print(f"DET storm-det-{args.index}", flush=True)
        pin = ray_tpu.put(b"\x5a" * int(args.put_mb * 1024 * 1024))
        print(f"PUT {pin.hex()} {pin.owner_address}", flush=True)
        print("VICTIM_READY", flush=True)

        deadline = time.monotonic() + args.duration
        ticks = completed = 0
        while time.monotonic() < deadline:
            refs = [_storm_leaf.remote(i) for i in range(2)]
            refs.append(_storm_tree.remote(args.tree_depth, args.fanout))
            vals = ray_tpu.get(refs, timeout=to)
            completed += len(refs) - 1 + vals[-1]  # leaves + tree node count
            ray_tpu.get(cnt.bump.remote(), timeout=to)
            completed += 1
            ticks += 1
            print(f"TICK {ticks} {completed}", flush=True)
            time.sleep(args.tick_sleep)
        if det is not None:
            ray_tpu.get(det.bump.remote(), timeout=to)
        assert pin is not None  # keep the put pinned for the whole run
        print(f"CLEAN {completed}", flush=True)
        ray_tpu.shutdown()
        return 0
    except BaseException as e:  # noqa: BLE001 - reported to the host verbatim
        print(f"DRIVER_ERROR {type(e).__name__}: {e}", flush=True)
        return 1


def run_verifier(args) -> int:
    """The 'next driver': a FRESH process that joins the cluster after the
    kills and resolves each dead job's detached actor by name — the
    ISSUE-mandated proof that detached lifetime really outlives its owner.
    Prints `DETOK <name> <value-before> <value-after-bump>` per actor."""
    try:
        ray_tpu.init(address=args.address)
        to = args.get_timeout
        for name in [n for n in args.names.split(",") if n]:
            h = ray_tpu.get_actor(name)
            v = ray_tpu.get(h.value.remote(), timeout=to)
            b = ray_tpu.get(h.bump.remote(), timeout=to)
            print(f"DETOK {name} {v} {b}", flush=True)
        ray_tpu.shutdown()
        return 0
    except BaseException as e:  # noqa: BLE001
        print(f"VERIFY_ERROR {type(e).__name__}: {e}", flush=True)
        return 1


# ------------------------------------------------------------- host harness

def _pump(rec: Dict[str, Any]) -> None:
    try:
        for line in rec["proc"].stdout:
            rec["lines"].append((time.monotonic(), line.rstrip("\n")))
    except Exception:
        pass
    rec["eof"] = time.monotonic()


def _tagged(rec: Dict[str, Any], tag: str) -> List:
    return [(t, ln) for t, ln in list(rec["lines"])
            if ln == tag or ln.startswith(tag + " ")]


def _wait_line(rec: Dict[str, Any], tag: str, timeout: float):
    deadline = time.monotonic() + timeout
    while True:
        hits = _tagged(rec, tag)
        if hits:
            return hits[0]
        if rec["proc"].poll() is not None and rec["eof"] is not None:
            hits = _tagged(rec, tag)
            return hits[0] if hits else None
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)


def _completed_at(rec: Dict[str, Any], t_edge: float) -> int:
    best = 0
    for t, ln in list(rec["lines"]):
        if ln.startswith("TICK ") and t <= t_edge:
            best = int(ln.split()[2])
    return best


def _spawn_driver(p: JobStormProfile, gcs: str, idx: int,
                  detached: bool) -> Dict[str, Any]:
    argv = [sys.executable, "-m", "ray_tpu.core.jobstorm", "--victim",
            "--address", gcs, "--index", str(idx),
            "--duration", str(p.driver_duration_s),
            "--put-mb", str(p.put_mb), "--fanout", str(p.fanout),
            "--tree-depth", str(p.tree_depth),
            "--tick-sleep", str(p.tick_sleep_s),
            "--get-timeout", str(p.get_timeout_s)]
    if detached:
        argv.append("--detached")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    rec: Dict[str, Any] = {"idx": idx, "proc": proc, "detached": detached,
                           "lines": [], "eof": None, "start": time.monotonic()}
    threading.Thread(target=_pump, args=(rec,), daemon=True,
                     name=f"jobstorm-pump-{idx}").start()
    return rec


def run_jobstorm(profile: Optional[JobStormProfile] = None,
                 out_path: Optional[str] = None) -> Dict[str, Any]:
    p = profile or JobStormProfile()
    assert p.n_kill < p.n_jobs, "need at least one surviving driver"
    rng = random.Random(p.seed)
    violations: List[str] = []
    phases: Dict[str, Any] = {}
    cluster: Optional[Cluster] = None
    drivers: List[Dict[str, Any]] = []
    stats_c = None
    t0 = time.monotonic()
    try:
        cluster = Cluster()
        cluster.add_node(num_cpus=8)
        cluster.add_node(num_cpus=4)
        cluster.connect()
        shm_prefixes = [r.store._prefix for r in cluster._raylets]
        stats_c = rpc.connect_with_retry(cluster.gcs_address, timeout=10)

        def gcs_jobs() -> Dict[str, dict]:
            st = stats_c.call("gcs_stats", timeout=10)
            return {j["job_id"]: j for j in st.get("jobs", [])}

        # ---- spawn N driver processes and wait for their steady state
        for i in range(p.n_jobs):
            drivers.append(_spawn_driver(p, cluster.gcs_address, i,
                                         detached=(i % p.detached_every == 0)))
        for rec in drivers:
            if _wait_line(rec, "VICTIM_READY", timeout=90.0) is None:
                violations.append(f"driver {rec['idx']} never became ready")
            jl = _tagged(rec, "JOB")
            pl = _tagged(rec, "PUT")
            rec["job_hex"] = jl[0][1].split()[1] if jl else None
            if pl:
                _, oid_hex, owner = pl[0][1].split()
                rec["put"] = (oid_hex, owner)
        if violations:
            raise RuntimeError(f"spawn failed: {violations}")
        t_ready = time.monotonic()
        phases["spawn"] = {"drivers": p.n_jobs,
                           "detached_owners":
                               sum(1 for r in drivers if r["detached"]),
                           "s": round(t_ready - t0, 2)}

        # ---- baseline throughput window
        time.sleep(p.baseline_s)

        # ---- the storm: seeded staggered SIGKILLs, >=1 detached owner dies
        kill_idx = sorted(rng.sample(range(p.n_jobs), p.n_kill))
        if not any(drivers[i]["detached"] for i in kill_idx):
            owners = [i for i in range(p.n_jobs) if drivers[i]["detached"]]
            kill_idx = sorted(set(kill_idx[1:] + [rng.choice(owners)]))
        t_first_kill = time.monotonic()
        for i in kill_idx:
            rec = drivers[i]
            os.kill(rec["proc"].pid, signal.SIGKILL)
            rec["killed_mono"] = time.monotonic()
            rec["killed_wall"] = time.time()
            time.sleep(p.kill_gap_s)

        # every killed job must go DEAD + carry a reap record within bound
        reap_lat: Dict[int, float] = {}
        for i in kill_idx:
            rec = drivers[i]
            deadline = rec["killed_mono"] + p.reap_bound_s
            entry = None
            while time.monotonic() < deadline:
                entry = gcs_jobs().get(rec["job_hex"])
                if entry and entry.get("status") == "DEAD" \
                        and entry.get("reap"):
                    break
                time.sleep(0.1)
            if not (entry and entry.get("status") == "DEAD"
                    and entry.get("reap")):
                violations.append(
                    f"job {rec['job_hex']} (driver {i}) not reaped within "
                    f"{p.reap_bound_s}s of SIGKILL")
            else:
                reap_lat[i] = max(0.0, entry["end_time"] - rec["killed_wall"])
        t_storm_end = time.monotonic()

        # ---- leak scan: nothing may still be attributed to a dead job
        dead_bin = {bytes.fromhex(drivers[i]["job_hex"]) for i in kill_idx
                    if drivers[i]["job_hex"]}
        leaked_workers = leaked_objs = -1
        settle_deadline = time.monotonic() + 5.0
        while time.monotonic() < settle_deadline:
            leaked_workers = leaked_objs = 0
            dead_handle_pids = 0
            for r in cluster._raylets:
                with r._lock:
                    for h in r._workers.values():
                        if (h.current_task is not None
                                and h.current_task.job_id.binary()
                                in dead_bin):
                            leaked_workers += 1
                        try:
                            os.kill(h.pid, 0)
                        except OSError:
                            dead_handle_pids += 1
                    leaked_objs += sum(1 for jid in r._obj_jobs.values()
                                       if jid in dead_bin)
            if leaked_workers == 0 and leaked_objs == 0 \
                    and dead_handle_pids == 0:
                break
            time.sleep(0.2)
        if leaked_workers:
            violations.append(
                f"{leaked_workers} worker(s) still running dead jobs' tasks")
        if leaked_objs:
            violations.append(
                f"{leaked_objs} object(s) still attributed to dead jobs")
        jobs_now = gcs_jobs()
        stranded_actors = 0
        for i in kill_idx:
            e = jobs_now.get(drivers[i]["job_hex"]) or {}
            stranded_actors += max(
                0, e.get("live_actors", 0) - e.get("detached_actors", 0))
        if stranded_actors:
            violations.append(
                f"{stranded_actors} non-detached actor(s) of dead jobs alive")
        phases["storm"] = {
            "killed": kill_idx,
            "reap_latency_s": {str(i): round(v, 3)
                               for i, v in reap_lat.items()},
            "reap_latency_max_s":
                round(max(reap_lat.values()), 3) if reap_lat else None,
            "leaked_workers": leaked_workers,
            "leaked_objects": leaked_objs,
            "stranded_actors": stranded_actors,
            "s": round(time.monotonic() - t_first_kill, 2)}

        # ---- cross-job get of reaped objects: typed OwnerDiedError, no hang
        xjob = {"typed_owner_died": 0, "mistyped": 0, "hung": 0}
        for i in kill_idx:
            put = drivers[i].get("put")
            if not put:
                continue
            oid_hex, owner = put
            ref = ObjectRef(ObjectID(bytes.fromhex(oid_hex)),
                            owner_address=owner)
            try:
                ray_tpu.get(ref, timeout=10.0)
                xjob["mistyped"] += 1
                violations.append(
                    f"cross-job get of dead job {i}'s object SUCCEEDED")
            except OwnerDiedError:
                xjob["typed_owner_died"] += 1
            except TimeoutError:
                xjob["hung"] += 1
                violations.append(
                    f"cross-job get of dead job {i}'s object timed out "
                    "instead of raising OwnerDiedError")
            except Exception as e:  # noqa: BLE001
                xjob["mistyped"] += 1
                violations.append(
                    f"cross-job get of dead job {i}'s object raised "
                    f"{type(e).__name__}, wanted OwnerDiedError")
        phases["cross_job_get"] = xjob

        # ---- detached actors answer a FRESH driver process, state intact
        det_names = [f"storm-det-{i}" for i in kill_idx
                     if drivers[i]["detached"]]
        det_ok = 0
        if det_names:
            argv = [sys.executable, "-m", "ray_tpu.core.jobstorm", "--verify",
                    "--address", cluster.gcs_address,
                    "--names", ",".join(det_names),
                    "--get-timeout", str(p.get_timeout_s)]
            env = dict(os.environ)
            env["PYTHONPATH"] = (_REPO_ROOT + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            env.setdefault("JAX_PLATFORMS", "cpu")
            vp = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  env=env)
            vrec: Dict[str, Any] = {"idx": "verify", "proc": vp, "lines": [],
                                    "eof": None}
            threading.Thread(target=_pump, args=(vrec,), daemon=True).start()
            drivers.append(vrec)  # cleanup sweep covers it too
            try:
                rc = vp.wait(timeout=90.0)
            except subprocess.TimeoutExpired:
                rc = None
                violations.append("detached-actor verifier driver hung")
            for _, ln in _tagged(vrec, "DETOK"):
                _, name, before, after = ln.split()
                det_ok += 1
                # bump() before the kill means value >= 1 survived the owner
                if int(before) < 1 or int(after) != int(before) + 1:
                    violations.append(
                        f"detached actor {name} lost its pre-kill state "
                        f"(value={before}, bump={after})")
            if rc != 0 or det_ok != len(det_names):
                err = _tagged(vrec, "VERIFY_ERROR")
                violations.append(
                    f"detached actors dead after owner kill: "
                    f"{det_ok}/{len(det_names)} answered "
                    f"(rc={rc}{', ' + err[0][1] if err else ''})")
        phases["detached"] = {"expected": len(det_names), "answered": det_ok}

        # ---- drain survivors: all must CLEAN (exit 0) with zero hung gets
        survivors = [r for r in drivers
                     if isinstance(r["idx"], int) and r["idx"] not in kill_idx]
        hung_drivers = errored = 0
        for rec in survivors:
            budget = max(1.0, rec["start"] + p.driver_duration_s
                         + p.drain_grace_s - time.monotonic())
            try:
                rc = rec["proc"].wait(timeout=budget)
            except subprocess.TimeoutExpired:
                hung_drivers += 1
                violations.append(
                    f"surviving driver {rec['idx']} hung past its duration "
                    f"+ {p.drain_grace_s}s grace")
                continue
            if rc != 0 or not _tagged(rec, "CLEAN"):
                errored += 1
                err = _tagged(rec, "DRIVER_ERROR")
                violations.append(
                    f"surviving driver {rec['idx']} did not drain clean "
                    f"(rc={rc}{', ' + err[0][1] if err else ''})")

        # ---- survivor throughput: storm-window rate vs pre-kill baseline
        floor = throughput_floor_frac()
        rates = {}
        for rec in survivors:
            base_n = (_completed_at(rec, t_first_kill)
                      - _completed_at(rec, t_ready))
            base_rate = base_n / max(1e-6, t_first_kill - t_ready)
            storm_n = (_completed_at(rec, t_storm_end)
                       - _completed_at(rec, t_first_kill))
            storm_rate = storm_n / max(1e-6, t_storm_end - t_first_kill)
            rates[str(rec["idx"])] = {
                "baseline_per_s": round(base_rate, 2),
                "storm_per_s": round(storm_rate, 2)}
            if base_n >= 3 and storm_rate < floor * base_rate:
                violations.append(
                    f"survivor {rec['idx']} throughput dipped below "
                    f"{floor:.2f}x baseline during the storm "
                    f"({storm_rate:.1f}/s vs {base_rate:.1f}/s)")
            if storm_n == 0 and t_storm_end - t_first_kill > 2.0:
                violations.append(
                    f"survivor {rec['idx']} starved (0 tasks) during the "
                    "storm window")
        phases["survivors"] = {"n": len(survivors),
                               "hung": hung_drivers, "errored": errored,
                               "throughput_floor_frac": round(floor, 3),
                               "rates": rates}

        # ---- control-plane counters for the artifact + sanity floor
        final = stats_c.call("gcs_stats", timeout=10)
        jf = final.get("job_failure", {})
        if jf.get("jobs_reaped", 0) < len(kill_idx):
            violations.append(
                f"gcs reap counter {jf.get('jobs_reaped')} < kills "
                f"{len(kill_idx)}")
        if det_names and jf.get("detached_spared", 0) < len(det_names):
            violations.append(
                f"detached_spared counter {jf.get('detached_spared')} < "
                f"detached owners killed {len(det_names)}")

        # ---- full teardown, then the shm-segment leak sweep
        ray_tpu.shutdown()
        cluster.shutdown()
        cluster = None
        leaked_shm = [f for f in os.listdir("/dev/shm")
                      if any(f.startswith(pre) for pre in shm_prefixes)]
        if leaked_shm:
            violations.append(
                f"{len(leaked_shm)} shm segment(s) leaked past cluster "
                f"shutdown: {leaked_shm[:4]}")
        phases["teardown"] = {"leaked_shm_segments": len(leaked_shm)}

        result = {
            "suite": "job storm (job failure domain)",
            "profile": {
                "n_jobs": p.n_jobs, "n_kill": p.n_kill,
                "detached_every": p.detached_every,
                "driver_duration_s": p.driver_duration_s,
                "tree_depth": p.tree_depth, "fanout": p.fanout,
                "put_mb": p.put_mb, "reap_bound_s": p.reap_bound_s,
                "seed": p.seed,
            },
            "phases": phases,
            "counters": {
                "jobs_reaped": jf.get("jobs_reaped", 0),
                "actors_killed": jf.get("actors_killed", 0),
                "detached_spared": jf.get("detached_spared", 0),
                "queued_cancelled": jf.get("queued_cancelled", 0),
                "workers_killed": jf.get("workers_killed", 0),
                "objects_dropped": jf.get("objects_dropped", 0),
                "bytes_dropped": jf.get("bytes_dropped", 0),
                "functions_freed": jf.get("functions_freed", 0),
            },
            "zero_hung": hung_drivers == 0 and xjob["hung"] == 0,
            "zero_leaks": (leaked_workers == 0 and leaked_objs == 0
                           and not leaked_shm),
            "detached_survived": det_ok == len(det_names),
            "violations": violations,
            "ok": not violations,
            "wall_s": round(time.monotonic() - t0, 2),
        }
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        return result
    finally:
        for rec in drivers:
            proc = rec.get("proc")
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
        if cluster is not None:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("jobstorm cluster shutdown failed")


# --------------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.WARNING)
    ap = argparse.ArgumentParser(
        description="job storm: the job failure domain under fire")
    ap.add_argument("--quick", action="store_true", help="small CI profile")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None,
                    help="write the result artifact here")
    # internal subprocess modes
    ap.add_argument("--victim", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--verify", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--address", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--index", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--detached", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--duration", type=float, default=20.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--put-mb", type=float, default=4.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--fanout", type=int, default=2, help=argparse.SUPPRESS)
    ap.add_argument("--tree-depth", type=int, default=2,
                    help=argparse.SUPPRESS)
    ap.add_argument("--tick-sleep", type=float, default=0.15,
                    help=argparse.SUPPRESS)
    ap.add_argument("--get-timeout", type=float, default=60.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--names", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.victim:
        return run_victim(args)
    if args.verify:
        return run_verifier(args)

    kw: Dict[str, Any] = (dict(QUICK_PROFILE) if args.quick
                          else full_profile_kwargs())
    kw["seed"] = args.seed
    p = JobStormProfile(**kw)
    result = run_jobstorm(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    c = result["counters"]
    st = result["phases"].get("storm", {})
    det = result["phases"].get("detached", {})
    sv = result["phases"].get("survivors", {})
    print(f"[jobstorm] seed={p.seed} jobs={p.n_jobs} killed={p.n_kill} | "
          f"reaped={c['jobs_reaped']} "
          f"reap_max={st.get('reap_latency_max_s')}s "
          f"actors_killed={c['actors_killed']} "
          f"detached_spared={c['detached_spared']} "
          f"workers_killed={c['workers_killed']} "
          f"objects_dropped={c['objects_dropped']} "
          f"({c['bytes_dropped']} B) "
          f"functions_freed={c['functions_freed']} | "
          f"detached_answered={det.get('answered')}/{det.get('expected')} "
          f"survivors_hung={sv.get('hung')} "
          f"leaks={st.get('leaked_workers')}w/"
          f"{st.get('leaked_objects')}o/"
          f"{result['phases'].get('teardown', {}).get('leaked_shm_segments')}shm",
          file=sys.stderr)
    if not result["ok"]:
        print("[jobstorm] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
