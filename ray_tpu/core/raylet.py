"""Raylet: the per-node manager.

Equivalent of the reference's `NodeManager` + `WorkerPool` + `LocalTaskManager`
(`src/ray/raylet/node_manager.h:115`, `worker_pool.h:156`,
`local_task_manager.h:58`): grants workers to queued tasks when resources are
available, spawns/reuses worker subprocesses, schedules across the cluster
with the hybrid policy using a resource view streamed from the GCS (the
reference's RaySyncer role), spills tasks back to other raylets, hosts the
node's shared-memory object store, and serves inter-node object transfer
(reference `ObjectManager`/`PullManager`/`PushManager`).
"""

from __future__ import annotations

import logging
import os
import socket as _socket_mod
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.core import rpc
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.exceptions import ObjectStoreFullError
from ray_tpu.core.object_store import (SharedObjectStore,
                                       sweep_stale_spill_dirs)
from ray_tpu.core.scheduler import NodeView, SchedulingPolicy
from ray_tpu.core.runtime_env_manager import env_key as _env_key
from ray_tpu.core.task_spec import TaskSpec, TaskType
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    conn: rpc.ServerConnection            # registration connection (for pushes)
    address: str                          # the worker's own core-worker server
    pid: int
    proc: Optional[subprocess.Popen] = None
    actor_id: Optional[ActorID] = None    # dedicated actor worker
    current_task: Optional[TaskSpec] = None
    task_started: float = 0.0             # monotonic start of current_task
    idle_since: float = field(default_factory=time.monotonic)
    env_key: Optional[str] = None         # pip runtime-env pool this worker serves
    is_driver: bool = False
    # resources held for the actor's lifetime: (bundle_key | None, demand)
    actor_charge: Optional[Tuple[Optional[Tuple], Dict[str, float]]] = None
    # chip indices granted for the current task / actor lifetime
    tpu_grant: Optional[Tuple[Optional[List[int]], float]] = None
    # recently completed tasks (task_id, owner_address, t_done): their
    # batched results may still sit in the worker's ResultBuffer when the
    # process dies, so unexpected disconnects fail them over to the owners
    recent_done: deque = field(default_factory=lambda: deque(maxlen=128))


@dataclass
class _QueuedTask:
    spec: TaskSpec
    spillback_count: int = 0
    # enqueue stamp (tracing epoch-us) for the lease span: submit-arrival to
    # worker-grant is the queueing stage of the critical path. 0.0 = untraced.
    queued_us: float = 0.0


class _PullBudget:
    """Byte-budget admission control for chunked pulls (reference
    PullManager's active-bundle quota, pull_manager.h:52): callers block
    until their object's bytes fit under the cap, so a burst of huge pulls
    can't overcommit store memory. Requests larger than the cap are clamped
    (a single object must always be admittable)."""

    def __init__(self, max_bytes: int):
        self._max = max(1, max_bytes)
        self._used = 0
        self._cv = threading.Condition()
        self._queue: deque = deque()  # FIFO tickets: no starvation of big pulls

    def acquire(self, n: int) -> None:
        n = min(n, self._max)
        ticket = object()
        with self._cv:
            self._queue.append(ticket)
            # Only the queue head may admit: without the ticket order a large
            # pull starves forever behind a stream of small ones re-grabbing
            # freed bytes.
            while self._queue[0] is not ticket or self._used + n > self._max:
                self._cv.wait(timeout=1.0)
            self._queue.popleft()
            self._used += n
            self._cv.notify_all()  # wake the next head

    def release(self, n: int) -> None:
        n = min(n, self._max)
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class Raylet:
    def __init__(
        self,
        gcs_address: str,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        host: str = "127.0.0.1",
        object_store_memory: Optional[int] = None,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        cfg = get_config()
        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        resources.setdefault("memory", 4 * 1024**3)
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.labels = dict(labels or {})
        self.worker_env = dict(worker_env or {})

        self._server = rpc.RpcServer(host)
        self._server.register_all(self)
        self.store = SharedObjectStore(capacity=object_store_memory)
        try:
            # collect spill dirs leaked by SIGKILLed prior stores (kill
            # storms do this every run); re-swept hourly by _reaper_loop
            sweep_stale_spill_dirs()
        except Exception:
            logger.exception("startup spill dir sweep failed")
        # bulk transfer side channel: raw sockets, shm->kernel->shm copies
        # only (see data_plane.py; reference object_manager.h:117 keeps bulk
        # chunk streams off the control plane the same way)
        from ray_tpu.core.data_plane import DataPlanePool, DataPlaneServer

        self._data_plane = DataPlaneServer(self.store, host=host)
        self._data_pool = DataPlanePool()

        self._lock = threading.RLock()
        self._policy = SchedulingPolicy()
        self._queue: deque[_QueuedTask] = deque()
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        # idle workers keyed by runtime-env pool: O(1) acquire per dispatch
        # instead of an O(n) scan over every idle worker of every env
        self._idle_pools: Dict[Optional[str], deque[WorkerID]] = {}
        # debounced resource broadcast (at most one report_resources notify
        # per resource_broadcast_period_ms, trailing edge guaranteed)
        from ray_tpu.util.debounce import Debouncer

        self._resource_report_debounce = Debouncer(
            self._send_resource_report,
            lambda: get_config().resource_broadcast_period_ms / 1000.0,
            skip_deferred=lambda: self._shutdown.is_set())
        self._starting: List[subprocess.Popen] = []
        self._starting_env: Dict[int, str] = {}  # pid -> env_key
        self._starting_envfile: Dict[int, str] = {}  # pid -> {ENVFILE} path
        self._env_spawning: set = set()          # env_keys mid-creation
        self._pending_actor_specs: deque = deque()
        from ray_tpu.core.runtime_env_manager import RuntimeEnvManager

        self._env_manager = RuntimeEnvManager()
        # warm worker pool: fork-template (zygote) processes + demand-driven
        # prestart; cold Popen spawns remain the fallback path
        from ray_tpu.core.worker_pool import WorkerPool

        self._worker_pool = WorkerPool(self)

        # cluster view: node_id hex -> {address, total, available, labels, alive}
        self._cluster_view: Dict[str, dict] = {}
        self._raylet_clients: Dict[str, rpc.RpcClient] = {}

        # per-pg bundle reservations: (pg_id, idx) -> remaining resources
        self._bundles: Dict[Tuple, Dict[str, float]] = {}
        self._bundles_committed: Dict[Tuple, bool] = {}
        # original reservation per bundle (re-reported to the GCS on
        # re-registration so a replacement head re-pins them) + prepare
        # time for 2PC orphan cleanup (a head that died between prepare
        # and commit leaks the reservation; the reaper returns it)
        self._bundle_reservations: Dict[Tuple, Dict[str, float]] = {}
        self._bundle_prepared_at: Dict[Tuple, float] = {}

        # head re-resolution: a new GCS address learned in-band (the
        # replacement head dials us and announces itself) overrides the
        # boot-time address; the address file (config gcs_address_file)
        # overrides both. Read on every reconnect attempt.
        self._gcs_address_override: Optional[str] = None
        # fencing: the highest head lease epoch this raylet has adopted.
        # Announces/publishes from a STALE head (epoch below this) are
        # logged and dropped — a fenced head cannot flap our GCS link.
        self._gcs_epoch: int = 0
        self._session_id: Optional[str] = None  # cluster session fingerprint
        self._fencing_drops = 0
        # node incarnation (partition failure domain): stamped by the GCS
        # at registration, echoed in every heartbeat. A typed fence reply
        # (this identity was declared dead while we were partitioned) makes
        # this raylet kill its workers — they host actor incarnations that
        # were restarted elsewhere — and rejoin as a FRESH node.
        self.incarnation: int = 0
        self._fenced_count = 0
        self._fencing_now = False  # one self-fence at a time
        # delta-encoded resource broadcasts: last applied publish seq (None
        # until the first full lands) + one catch-up fetch at a time
        self._bcast_seen_seq: Optional[int] = None
        self._catchup_inflight = False

        # object pulls in flight: object_id -> list[(conn, req_id, pin)]
        self._pending_pulls: Dict[ObjectID, List[Tuple]] = {}
        # zero-copy reader pins per server connection (id(conn) -> {oid:
        # count}): a reader worker that dies without unpinning has its
        # pins reaped when its connection drops — the cross-process half
        # of the pin lifecycle (finalizers cover the in-process half)
        self._conn_pins: Dict[int, Dict[ObjectID, int]] = {}
        # admission control for chunked pulls (reference pull_manager.h:52):
        # bounds the total bytes of concurrently-materializing inbound objects
        self._pull_budget = _PullBudget(cfg.pull_admission_max_bytes)

        self._gcs: Optional[rpc.RpcClient] = None
        # Per-chip TPU index assignment (reference worker GPU-id grants):
        # index -> remaining capacity. Integer demands take whole chips;
        # fractional demands pack onto one chip (best fit). Assigned ids
        # ship with the execute_task/become_actor push so get_tpu_ids()
        # reports DISJOINT devices across concurrent tasks.
        self._tpu_slots: Dict[int, float] = {
            i: 1.0 for i in range(int(self.resources_total.get("TPU", 0)))}
        self._start_time = time.time()
        # workers we SIGKILLed for memory pressure: their death notification
        # carries reason="oom" so exhausted retries surface OutOfMemoryError
        self._oom_killed: set = set()
        self.oom_kills_total = 0  # monotonic; read by memstorm/tests
        # workers we SIGKILLed for a force-cancel or a job reap: their death
        # notification carries reason="cancelled" so the owner (if any is
        # left) resolves the typed error with no retry
        self._cancel_killed: set = set()
        # primary copy -> owning job (stamped at obj_create): a job reap
        # deletes the dead job's objects by this index; entries die with
        # the object (delete/reap) and are pruned against the store on reap
        self._obj_jobs: Dict[ObjectID, bytes] = {}
        # recently reaped jobs: a reaped worker's death must not dial the
        # dead driver (the owner-notify paths skip these)
        self._reaped_jobs: Dict[bytes, float] = {}
        # cumulative reap counters, returned per reap + summed by the GCS
        self.job_reap_stats = {
            "jobs": 0, "queued_cancelled": 0, "workers_killed": 0,
            "actor_specs_dropped": 0, "objects_dropped": 0,
            "bytes_dropped": 0}
        # Raylets have no TaskEventBuffer (that is a worker-side object), so
        # lease spans ship on the heartbeat cadence via the same
        # task_events_batch channel: drain cursor + carry-over drop count +
        # NTP-style clock offset, mirroring task_events.py.
        self._spans_sent = 0
        self._spans_dropped_pending = 0
        self._clock_offset_us: Optional[float] = None
        self._clock_probe_at = 0.0
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------ boot
    def start(self) -> str:
        self._server.start()
        # Reconnecting link: a restarted GCS gets this node re-registered and
        # re-subscribed before any other call proceeds (GCS fault tolerance);
        # the resolver lets the link follow a REPLACEMENT head to a new
        # address (control-plane HA).
        self._gcs = rpc.ReconnectingClient(
            self.gcs_address, push_handler=self._on_gcs_push,
            on_reconnect=self._replay_gcs_registration,
            resolve=self._resolve_gcs_address,
            origin=self._server.address)
        self._joined_at = time.monotonic()
        reply = self._gcs.call("register_node", self._registration_payload())
        if isinstance(reply, dict) and reply.get("fenced"):
            # a brand-new node id can only be fenced by id collision or a
            # confused head — there is nothing to kill; surface it
            raise RuntimeError(
                f"GCS fenced our registration: {reply.get('reason')}")
        self._note_head_identity(reply)
        for n in reply["nodes"]:
            self._note_node(n)
        # warm node onboarding: pre-spawn fork templates for the fleet's
        # hot runtime-env keys so this node serves warm leases immediately
        # (node-join-to-first-warm-lease is the tracked number)
        self._worker_pool.prewarm(reply.get("hot_envs"))
        self._gcs.call("subscribe", {"channels": ["resources", "nodes", "control"],
                                     "origin": self._server.address})
        t = threading.Thread(target=self._heartbeat_loop, name="raylet-heartbeat", daemon=True)
        t.start()
        self._threads.append(t)
        t2 = threading.Thread(target=self._reaper_loop, name="raylet-reaper", daemon=True)
        t2.start()
        self._threads.append(t2)
        t3 = threading.Thread(target=self._memory_monitor_loop,
                              name="raylet-memory-monitor", daemon=True)
        t3.start()
        self._threads.append(t3)
        logger.info("raylet %s on %s resources=%s", self.node_id.hex()[:8],
                    self._server.address, self.resources_total)
        return self._server.address

    @property
    def address(self) -> str:
        return self._server.address

    def _registration_payload(self) -> dict:
        with self._lock:
            available = dict(self.resources_available)
            # PG bundle re-pinning: report the reservations this node still
            # holds so a replacement head (whose snapshot may trail a
            # commit) re-anchors its PG table to what the fleet holds
            bundles = [
                {"pg_id": key[0], "bundle_index": key[1],
                 "resources": dict(self._bundle_reservations.get(key, {})),
                 "committed": bool(self._bundles_committed.get(key))}
                for key in self._bundles]
        return {
            "node_id": self.node_id.binary(),
            "address": self._server.address,
            "resources": self.resources_total,
            # On RE-registration the node may be mid-load: a restarted GCS
            # must not advertise full capacity for a saturated node.
            "resources_available": available,
            "labels": self.labels,
            "bundles": bundles,
            "start_time": self._start_time,
            # incarnation echo: a re-register with the incarnation we hold
            # KEEPS it (no bump); 0 = fresh join, the GCS issues the next
            "incarnation": self.incarnation,
        }

    def _resolve_gcs_address(self) -> Optional[str]:
        """Current-best GCS address for a reconnect attempt: the address
        file (authoritative — operators/replacement heads publish there)
        beats the in-band announce, which beats the boot-time address.
        An empty/unreadable address file reads as "no answer" (keep the
        last-known address and retry), never as an address."""
        return rpc.read_gcs_address_file() or self._gcs_address_override

    def _note_head_identity(self, reply: dict) -> None:
        """Record the head's fencing epoch + cluster session id from a
        registration reply (the fingerprint promote_announce checks), and
        the node incarnation the head stamped us with."""
        epoch = reply.get("epoch")
        if epoch is not None:
            with self._lock:
                self._gcs_epoch = max(self._gcs_epoch, int(epoch))
        sid = reply.get("session_id")
        if sid:
            self._session_id = sid
        inc = reply.get("incarnation")
        if inc is not None:
            self.incarnation = int(inc)

    def _replay_gcs_registration(self, raw: rpc.RpcClient) -> None:
        """Re-register on a fresh GCS connection (uses the RAW client — the
        wrapper's lock is held during replay)."""
        reply = raw.call("register_node", self._registration_payload(), timeout=30)
        if isinstance(reply, dict) and reply.get("fenced"):
            # our identity was declared dead while we were away (partition
            # heal): kill the superseded workers and rejoin FRESH. Raising
            # aborts installing this connection; the fence itself kicks a
            # reconnect that registers the fresh identity.
            self._self_fence(reply.get("reason") or "registration fenced")
            raise rpc.RpcDisconnected(
                f"registration fenced: {reply.get('reason')}")
        # the link may have followed a head replacement: workers spawned
        # from now on (and rpc_get_gcs_address callers) get the live head
        self.gcs_address = raw.address
        self._note_head_identity(reply)
        for n in reply.get("nodes", []):
            self._note_node(n)
        with self._lock:
            self._bcast_seen_seq = None  # new head: wait for its first full
        raw.call("subscribe", {"channels": ["resources", "nodes", "control"],
                               "origin": self._server.address},
                 timeout=30)
        self._worker_pool.prewarm(reply.get("hot_envs"))
        logger.info("raylet %s re-registered with GCS at %s (epoch %s, "
                    "incarnation %s)", self.node_id.hex()[:8], raw.address,
                    reply.get("epoch"), reply.get("incarnation"))

    def _stale_announce(self, payload: dict, rpc_name: str) -> bool:
        """Fencing gate for head announces: an epoch below the one this
        raylet already adopted means a FENCED head is calling — log and
        drop (no GCS-client flap), count the rejection."""
        epoch = payload.get("epoch")
        if epoch is None:
            return False  # legacy announce: can't judge, accept
        with self._lock:
            if int(epoch) >= self._gcs_epoch:
                return False
            self._fencing_drops += 1
            known = self._gcs_epoch
        logger.warning(
            "raylet %s: dropped %s from STALE head %s (epoch %s < adopted "
            "%d)", self.node_id.hex()[:8], rpc_name,
            payload.get("address"), epoch, known)
        try:
            from ray_tpu.core.gcs import _head_metrics  # shared definition

            _head_metrics()["fencing"].inc(tags={"site": "raylet_announce"})
        except Exception:
            pass
        return True

    def _adopt_announce(self, payload: dict) -> None:
        """Record the announced head (address + epoch) and kick the
        reconnect loop off-thread (announce handlers run on the RPC loop;
        closing the client there would self-deadlock). A re-announce of the
        head we already have a live link to is a no-op — the paced
        re-announce backstop must not flap a healthy link."""
        address = payload["address"]
        with self._lock:
            self._gcs_epoch = max(self._gcs_epoch,
                                  int(payload.get("epoch", 0)))
        if address == self.gcs_address and self._gcs is not None \
                and not self._gcs.closed:
            cli = getattr(self._gcs, "_client", None)
            if cli is not None and not cli.closed:
                return  # already on this head over a live link
        with self._lock:
            self._bcast_seen_seq = None  # new head numbers its own stream
        self._gcs_address_override = address
        threading.Thread(target=self._kick_gcs_reconnect,
                         name="gcs-address-kick", daemon=True).start()

    def _kick_gcs_reconnect(self) -> None:
        gcs = self._gcs
        if gcs is None or gcs.closed:
            return
        cli = getattr(gcs, "_client", None)
        if cli is not None and not cli.closed:
            cli.close()  # on_disconnect schedules the reconnect

    def rpc_new_gcs_address(self, conn, req_id, payload):
        """In-band head-replacement announce: a replacement GCS restored
        this node from its snapshot and is telling us where it lives now.
        Records the override and kicks the reconnect loop by dropping the
        stale link. Epoch-fenced: a revived stale head's announce is
        dropped instead of flapping our link to the real head."""
        if self._stale_announce(payload, "new_gcs_address"):
            return False
        logger.info("raylet %s: GCS announced new address %s",
                    self.node_id.hex()[:8], payload["address"])
        self._adopt_announce(payload)
        return True

    def rpc_promote_announce(self, conn, req_id, payload):
        """Promoted-head announce with one-RPC re-adoption: epoch-fenced
        like new_gcs_address, and when the caller presents OUR cluster
        session id the reply carries this node's full registration payload
        — the new head adopts us from its snapshot-known provisional entry
        to a live node in this single round trip (no re-registration on
        the failover critical path). The background reconnect still runs
        (idempotently) to re-establish subscriptions/pushes."""
        if self._stale_announce(payload, "promote_announce"):
            return {"adopted": False, "reason": "stale_epoch"}
        logger.info("raylet %s: head promotion announced from %s (epoch %s)",
                    self.node_id.hex()[:8], payload.get("address"),
                    payload.get("epoch"))
        self._adopt_announce(payload)
        sid = payload.get("session_id")
        if not sid or sid != self._session_id:
            return {"adopted": False, "reason": "session_mismatch"}
        return {"adopted": True, **self._registration_payload()}

    def rpc_get_gcs_address(self, conn, req_id, payload):
        """Workers/drivers re-resolve the head through their raylet: the
        raylet's own reconnect loop tracks the replacement head, so its
        current gcs_address is the freshest in-band answer."""
        return self._gcs_address_override or self.gcs_address

    def note_first_warm_lease(self, seconds: float) -> None:
        """Pool callback: this node served its FIRST warm (forked) lease
        `seconds` after joining. One-shot, best-effort report to the GCS
        (ray_tpu_node_join_warm_lease_seconds + gcs_stats)."""
        try:
            self._gcs.notify("report_warm_lease", {
                "node_id": self.node_id.binary(),
                "join_to_first_warm_lease_s": seconds})
        except (OSError, RuntimeError) as e:
            logger.debug("warm-lease report lost (GCS down?): %s", e)

    def crash(self) -> None:
        """Whole-node crash for the chaos harness: the raylet, its workers
        and its fork templates die together — SIGKILL, no graceful
        teardown, no drain notify. The GCS must detect this through missed
        heartbeats alone, exactly like a real node loss."""
        self._shutdown.set()
        try:
            self._worker_pool.kill_all()
        except Exception:
            logger.exception("worker pool kill_all failed")
        with self._lock:
            workers = list(self._workers.values())
            starting = list(self._starting)
        for p in starting:
            try:
                p.kill()
            except OSError:
                pass
        for w in workers:
            if w.is_driver:
                continue  # the driver is not OUR process tree
            try:
                if w.proc is not None:
                    w.proc.kill()
                else:
                    os.kill(w.pid, 9)
            except OSError:
                pass
        if self._gcs:
            self._gcs.close()
        # snapshot under the lock: concurrent _peer() dials install into
        # this dict, and an unlocked iteration can raise mid-teardown
        with self._lock:
            clients = list(self._raylet_clients.values())
        for c in clients:
            c.close()
        self._data_pool.close()
        self._data_plane.stop()
        self._server.stop()
        self.store.shutdown()

    def _self_fence(self, reason: str) -> None:
        """Typed fence response received (our node identity was declared
        dead — e.g. a partition was healed after the cluster moved on):
        kill every worker and fork template on this node (their actor
        incarnations were restarted elsewhere; letting them keep answering
        is the two-addresses-per-named-actor split-brain), reset to a
        FRESH node identity, and re-register. The process, its server and
        its object store survive — only the node identity and the worker
        population are replaced. Runs off-thread: callers sit on the
        heartbeat loop or inside the GCS client's reconnect lock."""
        with self._lock:
            if self._fencing_now or self._shutdown.is_set():
                return
            self._fencing_now = True
            self._fenced_count += 1
        threading.Thread(target=self._do_self_fence, args=(reason,),
                         name="raylet-self-fence", daemon=True).start()

    def _do_self_fence(self, reason: str) -> None:
        old_hex = self.node_id.hex()[:8]
        logger.warning(
            "raylet %s FENCED (incarnation %d): %s — killing workers and "
            "rejoining as a fresh node", old_hex, self.incarnation, reason)
        try:
            with self._lock:
                workers = [w for w in self._workers.values()
                           if not w.is_driver]
                for w in workers:
                    # suppress actor_failed: those actors were restarted
                    # elsewhere while we were declared dead — reporting
                    # their "death" now would poke the LIVE instance
                    w.actor_id = None
                    self._workers.pop(w.worker_id, None)
                self._idle_pools.clear()
                starting = list(self._starting)
                self._starting.clear()
                starting_envs = list(self._starting_env.values())
                self._starting_env.clear()
                queued = [qt.spec for qt in self._queue]
                self._queue.clear()
                self._pending_actor_specs.clear()
                self._bundles.clear()
                self._bundles_committed.clear()
                self._bundle_reservations.clear()
                self._bundle_prepared_at.clear()
                self.resources_available = dict(self.resources_total)
                self._tpu_slots = {
                    i: 1.0 for i in range(
                        int(self.resources_total.get("TPU", 0)))}
            for p in starting:
                try:
                    p.kill()
                except OSError:
                    pass
            for ek in starting_envs:
                self._env_manager.release(ek)
            for w in workers:
                if w.env_key:
                    self._env_manager.release(w.env_key)
                try:
                    if w.proc is not None:
                        w.proc.kill()
                    else:
                        os.kill(w.pid, 9)
                except OSError:
                    pass
            # templates die too (their forked children would inherit the
            # superseded actor state); the pool stays SERVING — the fresh
            # identity reboots templates on demand / prewarm
            try:
                self._worker_pool.reset_for_fence()
            except Exception:
                logger.exception("worker pool fence reset failed")
            # tasks we held (queued or mid-run) fail over at their owners
            # exactly like a worker crash: retry budgets apply, owners on
            # live nodes resubmit through their own raylets
            for w in workers:
                if w.current_task is not None:
                    self._notify_owner_worker_died(w.current_task)
                self._failover_recent_done(w.recent_done)
            for spec in queued:
                self._notify_owner_worker_died(spec)
            # fresh identity: new node id, incarnation reissued by the GCS
            from ray_tpu.core.ids import NodeID as _NodeID

            with self._lock:
                self.node_id = _NodeID.from_random()
                self.incarnation = 0
                self._start_time = time.time()
                self._joined_at = time.monotonic()
                self._bcast_seen_seq = None
            logger.warning("raylet %s rejoining as fresh node %s after "
                           "fence", old_hex, self.node_id.hex()[:8])
            self._kick_gcs_reconnect()
        finally:
            with self._lock:
                self._fencing_now = False

    def stop(self) -> None:
        self._shutdown.set()
        self._worker_pool.stop()
        with self._lock:
            workers = list(self._workers.values())
            starting = list(self._starting)
            envfiles = list(self._starting_envfile.values())
            self._starting_envfile.clear()
        for path in envfiles:
            try:
                os.unlink(path)
            except OSError:
                pass
        for p in starting:
            try:
                p.terminate()
            except OSError:
                pass  # already exited
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass  # already exited
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        w.proc.kill()
                    except OSError:
                        pass  # exited between wait and kill
        if self._gcs:
            self._gcs.close()
        # snapshot under the lock: concurrent _peer() dials install into
        # this dict, and an unlocked iteration can raise mid-teardown
        with self._lock:
            clients = list(self._raylet_clients.values())
        for c in clients:
            c.close()
        self._data_pool.close()
        self._data_plane.stop()
        self._server.stop()
        self.store.shutdown()

    # ----------------------------------------------------- gcs pubsub intake
    def _on_gcs_push(self, method: str, payload):
        if method != "pubsub":
            return
        ch, msg = payload["channel"], payload["message"]
        if ch == "resources":
            self._apply_resource_broadcast(msg)
            self._schedule()
        elif ch == "nodes":
            if msg.get("event") == "removed":
                hexid = msg["node_id"].hex()
                with self._lock:
                    self._cluster_view.pop(hexid, None)
                    c = self._raylet_clients.pop(hexid, None)
                if c:
                    c.close()
        elif ch == "control":
            if msg.get("cmd") == "gc":
                with self._lock:
                    workers = list(self._workers.values())
                for w in workers:
                    if w.conn.alive:
                        w.conn.push("global_gc", {})

    def _apply_resource_broadcast(self, msg) -> None:
        """Apply one CH_RESOURCES publish. Three wire shapes: the legacy
        full-view dict, {"kind": "full"} (replace wholesale, reset the
        sequence), and {"kind": "delta"} (apply changed/removed on top of
        the view IF our last-applied seq is the delta's base — otherwise a
        gap: ignore it and pull one consistent full via get_resources_full).
        Epoch-stamped publishes from a head staler than the one we adopted
        are dropped."""
        if not isinstance(msg, dict) or "kind" not in msg:
            # legacy full-view dict (pre-delta heads)
            with self._lock:
                for hexid, v in msg.items():
                    if hexid == self.node_id.hex():
                        continue
                    self._cluster_view[hexid] = v
            return
        me = self.node_id.hex()
        need_catchup = False
        with self._lock:
            epoch = int(msg.get("epoch", 0))
            if epoch and epoch < self._gcs_epoch:
                self._fencing_drops += 1
                return  # stale head still publishing into a dead channel
            if msg["kind"] == "full":
                self._cluster_view = {h: v for h, v in msg["nodes"].items()
                                      if h != me}
                self._bcast_seen_seq = msg["seq"]
            elif self._bcast_seen_seq is not None \
                    and msg.get("prev") == self._bcast_seen_seq:
                for h, v in msg.get("changed", {}).items():
                    if h != me:
                        self._cluster_view[h] = v
                for h in msg.get("removed", ()):
                    self._cluster_view.pop(h, None)
                self._bcast_seen_seq = msg["seq"]
            else:
                # gap (missed publish / fresh subscription): one catch-up
                # fetch at a time; deltas keep arriving and are ignored
                # until the full view re-anchors the sequence
                if not self._catchup_inflight:
                    self._catchup_inflight = True
                    need_catchup = True
        if need_catchup:
            threading.Thread(target=self._broadcast_catchup,
                             name="bcast-catchup", daemon=True).start()

    def _broadcast_catchup(self) -> None:
        """Pull one consistent full resource view (we run OFF the push
        reader thread: a blocking call there would deadlock the reply)."""
        try:
            full = self._gcs.call("get_resources_full", {}, timeout=10)
        except Exception:
            logger.debug("broadcast catch-up fetch failed; next delta gap "
                         "will retry", exc_info=True)
            full = None
        me = self.node_id.hex()
        with self._lock:
            self._catchup_inflight = False
            if not isinstance(full, dict):
                return
            self._cluster_view = {h: v for h, v in full["nodes"].items()
                                  if h != me}
            self._bcast_seen_seq = full["seq"]
            self._gcs_epoch = max(self._gcs_epoch,
                                  int(full.get("epoch", 0)))
        self._schedule()

    def _note_node(self, n: dict) -> None:
        hexid = n["node_id"].hex()
        if hexid == self.node_id.hex():
            return
        with self._lock:
            self._cluster_view[hexid] = {
                "address": n["address"],
                "total": n["resources_total"],
                "available": n["resources_available"],
                "labels": n.get("labels", {}),
                "alive": n.get("alive", True),
            }

    def _peer(self, address: str) -> rpc.RpcClient:
        # Dial OUTSIDE self._lock: this is the raylet's main state lock,
        # and connect_with_retry spins its full timeout when the target is
        # dead (an owner whose node was killed). Holding the lock through
        # that stalls heartbeats and task dispatch for seconds per corpse.
        with self._lock:
            c = self._raylet_clients.get(address)
            if c is not None and not c.closed:
                return c
        c = rpc.connect_with_retry(address, timeout=3,
                                   origin=self._server.address)
        with self._lock:
            existing = self._raylet_clients.get(address)
            if existing is not None and not existing.closed:
                c.close()
                return existing
            self._raylet_clients[address] = c
            return c

    def _node_stats(self) -> dict:
        """Per-node physical utilization for the dashboard/state API
        (reference dashboard agent's psutil reporter,
        dashboard/modules/reporter/reporter_agent.py)."""
        try:
            import psutil

            vm = psutil.virtual_memory()
            st = self.store.stats()
            return {
                "cpu_percent": psutil.cpu_percent(interval=None),
                "mem_used": vm.used,
                "mem_total": vm.total,
                "object_store_used": st.get("used_bytes", 0),
                # storage failure-domain block: aggregated per node into
                # gcs_stats["storage"] (used/pinned/pool/spilled/degraded)
                "object_store": {
                    "used_bytes": st.get("used_bytes", 0),
                    "capacity_bytes": st.get("capacity_bytes", 0),
                    "pinned_bytes": st.get("pinned_bytes", 0),
                    "pool_bytes": st.get("pool_bytes", 0),
                    "spilled_bytes": st.get("spilled_bytes", 0),
                    "spill_degraded": st.get("spill_degraded", False),
                },
                "num_workers": len(self._workers),
            }
        except (OSError, ValueError, KeyError) as e:
            logger.debug("node stats unavailable: %s", e)
            return {}

    def _heartbeat_loop(self) -> None:
        period = get_config().health_check_period_ms / 1000.0
        while not self._shutdown.wait(period):
            with self._lock:
                demands = [self._effective_demand(qt.spec)
                           for qt in list(self._queue)[:100]]
            try:
                reply = self._gcs.call("heartbeat", {
                    "node_id": self.node_id.binary(),
                    "incarnation": self.incarnation,
                    "resources_available": dict(self.resources_available),
                    "pending_demands": demands,
                    "node_stats": self._node_stats(),
                    # recent lease traffic per env key: feeds the GCS
                    # hot-env table that joining nodes prewarm from
                    "hot_envs": self._worker_pool.hot_envs(),
                }, timeout=5)
                if isinstance(reply, dict):
                    if reply.get("fenced"):
                        # our identity was invalidated (declared dead during
                        # a partition): kill the superseded workers, rejoin
                        # as a fresh node
                        self._self_fence(reply.get("reason")
                                         or "heartbeat fenced")
                    elif reply.get("unknown"):
                        # this head never saw our registration (replacement
                        # head restored an older snapshot): re-register —
                        # same identity, workers intact
                        logger.warning(
                            "raylet %s unknown to the head; re-registering",
                            self.node_id.hex()[:8])
                        threading.Thread(target=self._kick_gcs_reconnect,
                                         name="gcs-rereg-kick",
                                         daemon=True).start()
            except Exception:
                if not self._shutdown.is_set():
                    logger.warning("heartbeat to GCS failed")
            # Periodic retry for queued tasks — independent of the GCS call
            # (local dispatch needs no GCS, and a down control plane is
            # exactly when the retry matters): scheduling is otherwise
            # event-driven (resource broadcasts fire on ACTIVITY), so on an
            # idle cluster a task queued behind a dead/suspect target would
            # starve forever — e.g. a lineage reconstruction spilled to a
            # node that died with no other traffic to re-trigger dispatch.
            try:
                if demands:
                    self._schedule()
                # Backstop for the actor-spawn pipeline (primary re-arm is
                # in the registration handler): if pending actor specs
                # outlive every in-flight spawn, respawn here.
                with self._lock:
                    if self._pending_actor_specs and not self._starting:
                        by_env: Dict = {}
                        for s in self._pending_actor_specs:
                            ek = _env_key(s.runtime_env)
                            by_env.setdefault(ek, [0, s.runtime_env])[0] += 1
                        for ek, (count, renv) in by_env.items():
                            self._maybe_spawn(ek, renv, needed=count)
            except Exception:
                if not self._shutdown.is_set():
                    logger.exception("periodic schedule retry failed")
            try:
                self._ship_spans()
            except Exception:
                logger.debug("raylet span flush failed", exc_info=True)

    def _ship_spans(self) -> None:
        """Flush locally recorded spans (lease spans, mostly) to the GCS on
        the heartbeat cadence via the task_events_batch channel — the raylet
        process has no TaskEventBuffer, so it ships its own tracing ring."""
        if not self.ship_spans or not tracing.enabled():
            return
        fresh, self._spans_sent, spans_dropped = tracing.drain(self._spans_sent)
        spans_dropped += self._spans_dropped_pending
        self._spans_dropped_pending = 0
        if not fresh and not spans_dropped:
            return
        now = time.monotonic()
        if self._clock_offset_us is None or now >= self._clock_probe_at:
            self._clock_probe_at = now + max(
                1.0, get_config().tracing_clock_probe_period_s)
            try:
                t0 = time.time() * 1e6
                reply = self._gcs.call("clock_probe", timeout=2)
                t2 = time.time() * 1e6
                self._clock_offset_us = reply["t1_us"] - (t0 + t2) / 2.0
            except Exception:
                logger.debug("raylet clock probe failed", exc_info=True)
        src = self.node_id.hex()
        payload = {
            "events": [],
            "dropped": 0,
            "src": src,
            "spans_dropped": spans_dropped,
            "profile_events": [{**e, "_src": src} for e in fresh],
        }
        if self._clock_offset_us is not None:
            payload["clock_offset_us"] = self._clock_offset_us
        try:
            delivered = self._gcs.try_notify("task_events_batch", payload)
        except Exception:
            delivered = False
        if not delivered:
            # spans are best-effort but their drop count is not (it is the
            # only record they existed) — re-ride it on the next heartbeat
            self._spans_dropped_pending += spans_dropped

    def _report_resources(self) -> None:
        """Debounced resource broadcast: at most one GCS notify per
        resource_broadcast_period_ms. Completions used to push one report
        (and one cluster-wide broadcast echo, which re-triggered _schedule
        on every subscribed raylet) per finished task; under a deep queue
        that was a measurable slice of the per-completion budget. A burst
        arms ONE trailing timer so the final post-burst state always lands
        within a period — never a stale view, never a notify storm."""
        self._resource_report_debounce()

    def _send_resource_report(self) -> None:
        try:
            self._gcs.notify("report_resources", {
                "node_id": self.node_id.binary(),
                "available": dict(self.resources_available),
            })
        except OSError as e:
            logger.debug("resource broadcast to GCS failed: %s", e)

    # ------------------------------------------------------- worker lifecycle
    def rpc_register_worker(self, conn, req_id, payload):
        wid: WorkerID = payload["worker_id"]
        handle = WorkerHandle(
            worker_id=wid, conn=conn, address=payload["address"], pid=payload["pid"],
        )
        with self._lock:
            # adopt the Popen (or forked-worker shim) if we started it
            for p in self._starting:
                if p.pid == payload["pid"]:
                    handle.proc = p
                    self._starting.remove(p)
                    break
            spawned_env = self._starting_env.pop(payload["pid"], None)
            handle.env_key = payload.get("env_key") or spawned_env
            self._workers[wid] = handle
            envfile = self._starting_envfile.pop(payload["pid"], None)
        if envfile is not None:
            # the worker booted: its {ENVFILE} env file has been consumed
            try:
                os.unlink(envfile)
            except OSError:
                pass
        if payload.get("worker_type") != "driver":
            self._worker_pool.note_registered(
                handle.proc, forked=bool(payload.get("forked")))
        if handle.env_key:
            # URI-style env refcount: alive while any worker serves it.
            # Bumped OUTSIDE the raylet lock (flock'd disk IO must never
            # stall scheduling), keyed off the SAME value the disconnect
            # release uses; if the worker vanished in the window, undo.
            self._env_manager.acquire(handle.env_key)
            with self._lock:
                gone = wid not in self._workers
            if gone:
                self._env_manager.release(handle.env_key)
        with self._lock:
            conn.on_close.append(lambda c, wid=wid: self._on_worker_disconnect(wid))
            if payload.get("worker_type") == "driver":
                handle.is_driver = True
                return {"node_id": self.node_id.binary(), "gcs_address": self.gcs_address}
            # a fresh worker: give it a pending actor spec (from the same
            # runtime-env pool) or mark idle
            spec = self._claim_pending_actor_spec(handle)
            if spec is not None:
                # Keep the spawn pipeline primed: creations that arrived
                # while the startup-concurrency budget was full never got a
                # spawn (budget 0), so each registration must re-arm it or
                # a 200-actor burst stalls once the first batch boots.
                remaining = sum(1 for s in self._pending_actor_specs
                                if _env_key(s.runtime_env) == handle.env_key)
                if remaining:
                    self._maybe_spawn(handle.env_key, spec.runtime_env,
                                      needed=remaining)
            else:
                self._idle_pools.setdefault(
                    handle.env_key, deque()).append(wid)
        if spawned_env:
            # the spawn lease handed off to the worker's own reference
            self._env_manager.release(spawned_env)
        self._schedule()
        return {"node_id": self.node_id.binary(), "gcs_address": self.gcs_address}

    def _build_worker_env(self, env_key: Optional[str] = None
                          ) -> Dict[str, str]:
        """Environment dict for a worker OR a fork-template process (the
        template captures it once; every forked child inherits it)."""
        env = dict(os.environ)
        env.update(self.worker_env)
        env.setdefault("JAX_PLATFORMS", "cpu")  # workers default to CPU JAX
        if env.get("JAX_PLATFORMS") == "cpu":
            # CPU-mode workers skip accelerator-plugin registration in
            # sitecustomize (it imports jax eagerly — multiple seconds per
            # worker spawn that most workers never need; jax still imports
            # normally from site-packages on first in-task use).
            env.pop("PALLAS_AXON_POOL_IPS", None)
        # Workers must find ray_tpu even when it is on sys.path but not
        # installed (driver ran `sys.path.insert`): prepend our package root.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (os.pathsep + existing if existing else "")
        if env_key is not None:
            env["RAY_TPU_RUNTIME_ENV_KEY"] = env_key
        else:
            env.pop("RAY_TPU_RUNTIME_ENV_KEY", None)
        env.pop("RAY_TPU_WORKER_FORKED", None)
        return env

    def _spawn_worker(self, env_key: Optional[str] = None,
                      runtime_env: Optional[dict] = None) -> bool:
        """Cold-spawn one worker; False when the spawn was suppressed
        (another spawn of a still-creating env is already in flight)."""
        env = self._build_worker_env(env_key)
        python = sys.executable
        if env_key is not None:
            # venv-backed pip env: resolve (and lazily create) the
            # interpreter off the scheduler thread, then spawn from it
            with self._lock:
                if env_key in self._env_spawning:
                    return False  # one spawn per env at a time while creating
                self._env_spawning.add(env_key)

            def create_and_spawn():
                # spawn LEASE: hold the env's refcount from resolution until
                # the worker registers (which takes its own reference), so a
                # gc tick can't delete the env out from under a booting
                # worker; released at registration or on spawn failure
                self._env_manager.acquire(env_key)
                try:
                    ctx = self._env_manager.context_for(runtime_env)
                    env.update(ctx.env_vars)  # plugin-contributed worker env
                    self._launch_worker(ctx.python, env,
                                        command_prefix=ctx.command_prefix)
                except Exception as e:  # ANY plugin/spawn failure fails tasks
                    logger.warning("%s", e)
                    self._env_manager.release(env_key)
                    self._fail_env_tasks(env_key, str(e))
                finally:
                    with self._lock:
                        self._env_spawning.discard(env_key)

            threading.Thread(target=create_and_spawn, daemon=True,
                             name="runtime-env-create").start()
            return True
        self._launch_worker(python, env)
        return True

    def _launch_worker(self, python: str, env: Dict[str, str],
                       command_prefix=None) -> None:
        argv = [python, "-m", "ray_tpu.core.worker_main",
                "--raylet", self._server.address, "--gcs", self.gcs_address,
                "--node-id", self.node_id.hex()]
        envfile = None
        if command_prefix:
            prefix = list(command_prefix)
            if "{ENVFILE}" in prefix:
                # container boundary: the worker env crosses via an env
                # file (Popen's env= only reaches the engine CLI itself)
                import tempfile

                fd, envfile = tempfile.mkstemp(prefix="rtpu-worker-",
                                               suffix=".env")
                with os.fdopen(fd, "w") as f:
                    for k, v in env.items():
                        if "\n" not in v:
                            f.write(f"{k}={v}\n")
                prefix = [envfile if a == "{ENVFILE}" else a for a in prefix]
            argv = prefix + argv
        proc = subprocess.Popen(argv, env=env)
        with self._lock:
            self._starting.append(proc)
            key = env.get("RAY_TPU_RUNTIME_ENV_KEY")
            if key:
                self._starting_env[proc.pid] = key
            if envfile is not None:
                # tracked for cleanup at registration / startup-death (the
                # reaper also sweeps stale files as a crash backstop)
                self._starting_envfile[proc.pid] = envfile

    def _fail_env_tasks(self, env_key: str, msg: str) -> None:
        """Fail every queued task/actor whose pip env could not be built."""
        with self._lock:
            bad_tasks = [qt for qt in self._queue
                         if _env_key(qt.spec.runtime_env) == env_key]
            for qt in bad_tasks:
                self._queue.remove(qt)
            bad_actors = [s for s in self._pending_actor_specs
                          if _env_key(s.runtime_env) == env_key]
            for s in bad_actors:
                self._pending_actor_specs.remove(s)
        for qt in bad_tasks:
            self._notify_owner_task_failed(qt.spec, msg)
        for s in bad_actors:
            try:
                self._gcs.notify("actor_failed", {
                    "actor_id": s.actor_id, "reason": msg,
                    "node_id": self.node_id.binary()})
            except OSError as e:
                logger.warning("actor_failed notify lost (GCS down?): %s", e)

    def _on_worker_disconnect(self, wid: WorkerID) -> None:
        with self._lock:
            handle = self._workers.pop(wid, None)
            if handle is None:
                return
        if handle.env_key:
            self._env_manager.release(handle.env_key)
        with self._lock:
            pool = self._idle_pools.get(handle.env_key)
            if pool is not None:
                try:
                    pool.remove(wid)
                except ValueError:
                    pass
            spec = handle.current_task
            actor_id = handle.actor_id
        if self._shutdown.is_set():
            return
        was_oom = wid in self._oom_killed
        self._oom_killed.discard(wid)
        was_cancel = wid in self._cancel_killed
        self._cancel_killed.discard(wid)
        if handle.tpu_grant is not None:
            self._release_tpus(*handle.tpu_grant)
            handle.tpu_grant = None
        if spec is not None:
            self._release_resources(spec)
            if not self._job_reaped(spec.job_id):
                # reaped jobs skip the notify: the owner IS the dead driver
                # (or one of its killed workers) — dialing it buys nothing
                reason = ("cancelled" if was_cancel
                          else "oom" if was_oom else "")
                self._notify_owner_worker_died(spec, reason=reason)
        # Batched-result loss failover: tasks completed in the last few
        # flush intervals may have died with their results still in the
        # worker's ResultBuffer (task_done precedes result delivery under
        # load). task_worker_died is idempotent at the owner — a task whose
        # results already landed was popped from its pending table — so
        # over-notifying is safe; an owner that DID lose the results retries
        # or fails the task instead of hanging on it forever. Clean exits
        # (max_calls recycle, idle kill) pop the handle before the
        # disconnect fires and never reach this; retiring workers get the
        # same backstop after a grace delay in rpc_task_done.
        self._failover_recent_done(handle.recent_done)
        self._release_actor_charge(handle)
        if actor_id is not None:
            try:
                self._gcs.notify("actor_failed", {
                    "actor_id": actor_id,
                    "reason": f"worker process {handle.pid} died",
                    # node-scoped: the GCS ignores this if the actor is no
                    # longer hosted here (late report racing a restart)
                    "node_id": self.node_id.binary()})
            except OSError as e:
                logger.warning("actor_failed notify lost (GCS down?): %s", e)
        self._schedule()

    def _failover_recent_done(self, recent_done, extra_window: float = 0.0
                              ) -> None:
        """Notify owners of recently completed tasks that their worker is
        gone; owners whose results already landed treat it as a no-op. The
        window scales with the configured flush interval — results can sit
        buffered in the worker for about that long (`extra_window` covers
        deliberate delays, e.g. the retiring-worker grace). Entries group
        per owner and an owner is dialed ONCE: a dead owner (the common
        paired failure — driver died, then its worker) costs one bounded
        connect attempt, not one per completed task."""
        window = extra_window + max(
            5.0, 10 * get_config().result_buffer_flush_interval_ms / 1000.0)
        now = time.monotonic()
        by_owner: Dict[str, list] = {}
        for task_id, owner, t_done in list(recent_done):
            if now - t_done <= window:
                by_owner.setdefault(owner, []).append(task_id)
        for owner, task_ids in by_owner.items():
            try:
                peer = self._peer(owner)
                for task_id in task_ids:
                    peer.notify("task_worker_died",
                                {"task_id": task_id, "reason": ""})
            except Exception:
                logger.debug("recent-done failover notify to %s lost", owner)

    def _notify_owner_task_failed(self, spec: TaskSpec, msg: str) -> None:
        try:
            owner = self._peer(spec.owner_address)
            owner.notify("task_failed", {"task_id": spec.task_id, "error": msg})
        except Exception:
            logger.warning("could not notify owner of failed task %s", spec.task_id)

    def _notify_owner_worker_died(self, spec: TaskSpec, reason: str = "") -> None:
        try:
            owner = self._peer(spec.owner_address)
            owner.notify("task_worker_died",
                         {"task_id": spec.task_id, "reason": reason})
        except Exception:
            logger.warning("could not notify owner of dead worker for task %s", spec.task_id)

    # ------------------------------------------------- cancellation / reap
    def _job_reaped(self, job_id) -> bool:
        key = job_id.binary() if hasattr(job_id, "binary") else job_id
        with self._lock:
            return key in self._reaped_jobs

    def rpc_cancel_task(self, conn, req_id, payload):
        """Owner-side cancel reaching the task's node of record. Queued:
        dequeue + typed ack to the owner (no children can exist — the task
        never ran). Running: push the cooperative interrupt to the hosting
        worker (which fans out any recursive child cancels as their owner);
        force=True SIGKILLs after a short grace so the interrupt gets a
        chance to propagate first. Not here at all: forward once along the
        owner-recorded spill hop, else stay silent — the owner's failsafe
        owns resolution for acks lost in transit."""
        task_id: TaskID = payload["task_id"]
        force = bool(payload.get("force"))
        with self._lock:
            qt = next((q for q in self._queue
                       if q.spec.task_id == task_id), None)
            if qt is not None:
                self._queue.remove(qt)
        if qt is not None:
            try:
                self._peer(qt.spec.owner_address).notify("task_cancelled", {
                    "task_id": task_id,
                    "detail": (f"task {qt.spec.method_name} was cancelled "
                               f"while queued")})
            except Exception:
                logger.debug("task_cancelled ack lost", exc_info=True)
            return True
        with self._lock:
            target = next((h for h in self._workers.values()
                           if h.current_task is not None
                           and h.current_task.task_id == task_id), None)
        if target is None:
            hint = payload.get("spilled_node_id")
            if hint is not None and hint != self.node_id.binary():
                v = self._cluster_view.get(hint.hex())
                if v is not None:
                    fwd = dict(payload)
                    fwd.pop("spilled_node_id", None)
                    try:
                        self._peer(v["address"]).notify("cancel_task", fwd)
                    except Exception:
                        logger.debug("cancel forward to %s lost",
                                     hint.hex()[:8], exc_info=True)
            return True
        try:
            target.conn.push("cancel_task", {
                "task_id": task_id, "force": force,
                "recursive": bool(payload.get("recursive"))})
        except Exception:
            logger.debug("cancel push to worker %d lost", target.pid,
                         exc_info=True)
        if force:
            t = threading.Timer(
                get_config().task_cancel_force_grace_ms / 1000.0,
                self._force_kill_cancelled, args=(task_id,))
            t.daemon = True
            t.start()
        return True

    def _force_kill_cancelled(self, task_id: TaskID) -> None:
        """force=True escalation: the cooperative grace expired and a
        worker is STILL on the task — SIGKILL it. The disconnect path then
        reports reason="cancelled" and the owner resolves typed,
        non-retryable (it zeroed the retry budget at cancel)."""
        with self._lock:
            target = next((h for h in self._workers.values()
                           if h.current_task is not None
                           and h.current_task.task_id == task_id), None)
            if target is None:
                return  # interrupt landed (or task finished) in the grace
            self._cancel_killed.add(target.worker_id)
        logger.info("force-cancel: killing worker %d still running task "
                    "after grace", target.pid)
        try:
            if target.proc is not None:
                target.proc.kill()
            else:
                os.kill(target.pid, 9)
        except OSError:
            self._cancel_killed.discard(target.worker_id)

    def rpc_reap_job(self, conn, req_id, payload):
        """GCS push: a job died (driver SIGKILL/OOM/preemption) — purge
        every trace of it from this node: queued tasks (no owner ack; the
        owner IS the corpse), running task workers (SIGKILL, marked so the
        disconnect path skips the dead-owner notify), pending actor specs,
        and the job's primary object copies. Actor WORKERS are killed by
        the GCS's per-actor kill_actor_worker pushes riding the same reap —
        not here — so a detached actor's worker is never touched. Returns
        this node's reap counters for the GCS rollup."""
        job_id: bytes = payload["job_id"]
        pace = max(0.0, get_config().job_reap_pacing_ms / 1000.0)
        now = time.monotonic()
        with self._lock:
            self._reaped_jobs[job_id] = now
            for k, ts in list(self._reaped_jobs.items()):
                if now - ts > 600.0:
                    del self._reaped_jobs[k]
            doomed_q = [qt for qt in self._queue
                        if qt.spec.job_id.binary() == job_id]
            for qt in doomed_q:
                self._queue.remove(qt)
            doomed_specs = [
                s for s in self._pending_actor_specs
                if getattr(s, "job_id", None) is not None
                and s.job_id.binary() == job_id]
            for s in doomed_specs:
                self._pending_actor_specs.remove(s)
            victims = [h for h in self._workers.values()
                       if h.actor_id is None
                       and h.current_task is not None
                       and h.current_task.job_id.binary() == job_id]
            for h in victims:
                self._cancel_killed.add(h.worker_id)
            doomed_objs = [oid for oid, jid in self._obj_jobs.items()
                           if jid == job_id]
            for oid in doomed_objs:
                self._obj_jobs.pop(oid, None)
        for h in victims:
            try:
                if h.proc is not None:
                    h.proc.kill()
                else:
                    os.kill(h.pid, 9)
            except OSError:
                pass  # exited on its own between pick and kill
            if pace:
                time.sleep(pace)
        bytes_dropped = 0
        for oid in doomed_objs:
            loc = self.store.lookup(oid)
            if loc is not None:
                bytes_dropped += loc[1]
            self.store.delete(oid)
            self._resolve_pulls(oid, "owner job reaped")
        # spawn demand queued for the purged backlog would fork workers
        # into a vacuum; serve re-reads live backlog, this just drops the
        # stale figures ahead of it
        self._worker_pool.shed_demand()
        counters = {
            "queued_cancelled": len(doomed_q),
            "workers_killed": len(victims),
            "actor_specs_dropped": len(doomed_specs),
            "objects_dropped": len(doomed_objs),
            "bytes_dropped": bytes_dropped,
        }
        with self._lock:
            self.job_reap_stats["jobs"] += 1
            for k, v in counters.items():
                self.job_reap_stats[k] += v
        if any(counters.values()):
            logger.info(
                "reaped job %s: %d queued tasks, %d workers, %d pending "
                "actors, %d objects (%d bytes)", job_id.hex()[:8],
                counters["queued_cancelled"], counters["workers_killed"],
                counters["actor_specs_dropped"], counters["objects_dropped"],
                bytes_dropped)
        self._schedule()
        return counters

    # ---------------------------------------------------------- memory guard
    def _memory_monitor_loop(self) -> None:
        """Node memory watchdog (reference MemoryMonitor, memory_monitor.h:52):
        when usage crosses the watermark, SIGKILL a worker running the
        NEWEST retriable task (reference retriable-LIFO killing policy,
        worker_killing_policy.h:34). The owner resubmits it (kills are
        cooldown-paced so a retry has a window to succeed); with retries
        exhausted the caller sees OutOfMemoryError."""
        try:
            import psutil
        except ImportError:
            return
        cfg = get_config()
        period = cfg.memory_monitor_refresh_ms / 1000.0
        last_kill = 0.0
        while not self._shutdown.wait(period):
            try:
                usage = self._memory_usage_fraction(psutil)
            except (OSError, ValueError) as e:
                logger.debug("memory probe failed: %s", e)
                continue
            if usage <= cfg.memory_usage_threshold:
                continue
            # Cooldown between kills: a SIGKILLed worker's memory takes time
            # to return to the OS; killing every tick would cascade through
            # innocent workers before pressure can drop.
            now = time.monotonic()
            if now - last_kill < cfg.memory_monitor_kill_cooldown_ms / 1000.0:
                continue
            if self._kill_memory_victim(usage):
                last_kill = time.monotonic()

    def _kill_memory_victim(self, usage: float) -> bool:
        """Pick, flag and SIGKILL atomically under the lock so the signal
        can't land on a worker that finished its task (or became an actor
        worker) between selection and kill."""
        cfg = get_config()
        min_age = cfg.memory_monitor_min_task_age_ms / 1000.0
        now = time.monotonic()
        with self._lock:
            candidates = [
                w for w in self._workers.values()
                if w.current_task is not None and not w.is_driver
                and w.actor_id is None and now - w.task_started >= min_age]
            if not candidates:
                return False
            # Retriable first, newest first (cheapest work to redo); never
            # drivers or actor workers (actor death is a bigger blast
            # radius — reference group-by-owner policy escalates there).
            retriable = [w for w in candidates
                         if w.current_task.max_retries != 0]
            pool = retriable or candidates
            victim = max(pool, key=lambda w: w.task_started)
            logger.warning(
                "memory pressure %.0f%% > %.0f%%: killing worker %d running "
                "task %s", usage * 100,
                get_config().memory_usage_threshold * 100, victim.pid,
                victim.current_task.method_name)
            self._oom_killed.add(victim.worker_id)
            try:
                if victim.proc is not None:
                    victim.proc.kill()
                else:
                    os.kill(victim.pid, 9)
            except OSError:
                # it exited on its own between pick and kill
                self._oom_killed.discard(victim.worker_id)
                return False
            self.oom_kills_total += 1
        return True

    def _memory_usage_fraction(self, psutil) -> float:
        cfg = get_config()
        budget = cfg.memory_monitor_worker_budget_bytes
        if budget > 0:
            # Budget mode counts only the workers the kill policy may touch:
            # actor-held memory must not trigger an endless kill loop of
            # innocent task workers it can never relieve.
            with self._lock:
                pids = [w.pid for w in self._workers.values()
                        if not w.is_driver and w.actor_id is None]
            total = 0
            for pid in pids:
                try:
                    total += psutil.Process(pid).memory_info().rss
                except psutil.Error:
                    pass  # raced a worker exit
            return total / budget
        return psutil.virtual_memory().percent / 100.0

    def _reaper_loop(self) -> None:
        """Reap dead spawned processes + kill long-idle workers + reclaim
        long-unreferenced runtime envs + collect stale spill dirs."""
        cfg = get_config()
        last_env_gc = time.monotonic()
        last_spill_gc = time.monotonic()
        while not self._shutdown.wait(1.0):
            if time.monotonic() - last_spill_gc >= 3600.0:
                # hourly: spill dirs leaked by SIGKILLed stores (keyed by
                # pid; the startup sweep in __init__ covers the common
                # case, this covers raylets outliving their killed peers)
                last_spill_gc = time.monotonic()
                try:
                    sweep_stale_spill_dirs()
                except Exception:
                    logger.exception("stale spill dir sweep failed")
            if time.monotonic() - last_env_gc >= 60.0:
                last_env_gc = time.monotonic()
                try:
                    # idle grace matches the worker-pool idle policy: an env
                    # whose last worker left may get a new task momentarily
                    self._env_manager.gc(
                        min_idle_s=cfg.idle_worker_killing_time_s)
                except Exception:
                    logger.exception("runtime env gc failed")
                self._sweep_stale_envfiles()
            # 2PC orphan cleanup: a bundle PREPARED but never committed
            # means the head died (or gave up) between phases — nothing
            # will ever commit or return it, so the reservation would leak
            # node capacity forever. Return it after the prepare timeout
            # (a resumed creation re-prepares it idempotently first).
            now_mono = time.monotonic()
            prep_timeout = cfg.bundle_prepare_timeout_s
            with self._lock:
                orphans = [k for k, t in self._bundle_prepared_at.items()
                           if not self._bundles_committed.get(k)
                           and now_mono - t > prep_timeout]
            for pg_id, idx in orphans:
                pid = pg_id.hex()[:8] if hasattr(pg_id, "hex") else str(pg_id)
                logger.warning(
                    "returning orphaned uncommitted bundle (%s, %d): "
                    "prepared over %.0fs ago, never committed",
                    pid, idx, prep_timeout)
                self.rpc_return_bundle(None, 0, {
                    "pg_id": pg_id, "bundle_index": idx})
            with self._lock:
                starting = list(self._starting)
            for p in starting:
                expired = (getattr(p, "forked", False) and p.poll() is None
                           and time.monotonic() - p.started_at
                           > cfg.worker_register_timeout_s)
                if expired:
                    # a forked worker that never registered within the
                    # budget: signal-0 liveness can't be trusted (the
                    # template reaped it and the pid may be an unrelated
                    # process by now) — expire the slot, return its lease
                    logger.warning(
                        "forked worker pid %d never registered within %ss; "
                        "expiring", p.pid, cfg.worker_register_timeout_s)
                if p.poll() is not None or expired:
                    with self._lock:
                        try:
                            self._starting.remove(p)
                        except ValueError:
                            pass
                        dead_env = self._starting_env.pop(p.pid, None)
                        dead_envfile = self._starting_envfile.pop(p.pid, None)
                    if dead_env:
                        # died before registering: return its spawn lease
                        self._env_manager.release(dead_env)
                    if dead_envfile:
                        try:
                            os.unlink(dead_envfile)
                        except OSError:
                            pass
                    logger.warning("worker pid %d exited during startup rc=%s", p.pid, p.returncode)
            # warm-pool upkeep: dead templates -> backoff respawn state,
            # idle env templates closed, default-env prestart floor topped up
            try:
                self._worker_pool.health_tick()
            except Exception:
                logger.exception("worker pool health tick failed")
            # idle killing (the default-env pool never shrinks below the
            # prestart floor: killing a floor worker would just respawn it
            # next tick — a kill/respawn flap instead of a warm reserve)
            now = time.monotonic()
            to_kill: List[WorkerHandle] = []
            with self._lock:
                for pool_key, pool in self._idle_pools.items():
                    keep = self._worker_pool.floor() if pool_key is None else 0
                    for wid in list(pool):
                        if len(pool) <= keep:
                            break
                        w = self._workers.get(wid)
                        # no `proc is not None` guard: the exit push below
                        # is graceful for ANY worker, and a forked worker
                        # that registered after its shim expired has
                        # proc=None — it must still be idle-killable
                        if w and now - w.idle_since > cfg.idle_worker_killing_time_s:
                            pool.remove(wid)
                            self._workers.pop(wid, None)
                            to_kill.append(w)
            for w in to_kill:
                if w.env_key:
                    # popped here, so _on_worker_disconnect won't release
                    self._env_manager.release(w.env_key)
                try:
                    w.conn.push("exit", {})
                except OSError:
                    pass  # connection already dropped; process reaper owns it

    def _sweep_stale_envfiles(self, max_age_s: float = 3600.0) -> None:
        """Crash backstop for the tracked {ENVFILE} cleanup: a raylet that
        died between mkstemp and registration leaves rtpu-worker-*.env
        files behind; sweep ones old enough that no live spawn owns them."""
        import glob
        import tempfile

        with self._lock:
            live = set(self._starting_envfile.values())
        cutoff = time.time() - max_age_s
        pattern = os.path.join(tempfile.gettempdir(), "rtpu-worker-*.env")
        for path in glob.glob(pattern):
            if path in live:
                continue
            try:
                if os.path.getmtime(path) < cutoff:
                    os.unlink(path)
            except OSError:
                pass  # raced another sweeper or the owner

    # -------------------------------------------------------- observability
    def rpc_worker_pool_stats(self, conn, req_id, payload):
        """Warm/cold start counters + fork latency percentiles + template
        states (envelope, burst harness, dashboards)."""
        return self._worker_pool.stats()

    def rpc_object_store_stats(self, conn, req_id, payload):
        """Store usage for `ray_tpu memory` (reference scripts.py:1881)."""
        return {"node_id": self.node_id.binary(), **self.store.stats()}

    def rpc_list_workers(self, conn, req_id, payload):
        """Worker pids/state for `ray_tpu stack` + debugging."""
        with self._lock:
            return [{
                "pid": w.pid,
                "worker_id": w.worker_id,
                "actor_id": w.actor_id.binary() if w.actor_id else None,
                "idle": w.current_task is None and w.actor_id is None,
                "env_key": w.env_key,
            } for w in self._workers.values() if not w.is_driver]

    def rpc_profile_worker(self, conn, req_id, payload):
        """Start an on-demand cpu/memory profile in a worker (reference
        dashboard's py-spy/memray trigger, `profile_manager.py` role).
        Returns a token; poll rpc_profile_result for the report."""
        import uuid

        pid = payload.get("pid")
        token = uuid.uuid4().hex
        with self._lock:
            targets = [w for w in self._workers.values()
                       if not w.is_driver and (pid is None or w.pid == pid)]
        if pid is not None and not targets:
            return {"error": f"no worker with pid {pid} on this node"}
        started = []
        for w in targets:
            if w.conn.alive:
                w.conn.push("profile", {
                    "token": f"{token}-{w.pid}",
                    "profile_kind": payload.get("profile_kind", "cpu"),
                    "duration_s": payload.get("duration_s", 5.0),
                })
                started.append({"pid": w.pid, "token": f"{token}-{w.pid}"})
        return {"started": started}

    def rpc_profile_result(self, conn, req_id, payload):
        from ray_tpu.util.profiler import read_profile_result

        return {"result": read_profile_result(payload["token"])}

    # set True by node_main (standalone daemon): chaos kill may hard-exit.
    # In-process raylets (driver-embedded head, test Cluster) refuse — the
    # exit would take the driver down with it.
    allow_chaos_kill = False

    # set True by node_main: a STANDALONE raylet process ships its own
    # tracing ring (it has no worker-side TaskEventBuffer). In-process
    # raylets must leave shipping to the driver worker's buffer — two
    # drain cursors on one process-wide ring would double-ship every span.
    ship_spans = False

    def rpc_worker_log(self, conn, req_id, payload):
        """Worker stdout/stderr lines -> GCS CH_LOGS fan-out."""
        payload = dict(payload)
        payload["node_id"] = self.node_id.binary()
        try:
            self._gcs.notify("publish_logs", payload)
        except (OSError, RuntimeError):
            pass  # GCS reconnecting; log fan-out is best-effort
        return True

    def rpc_die(self, conn, req_id, payload):
        """Chaos kill for fault-injection tests (reference
        `ray kill_random_node`, scripts.py:1325): hard-exit the node."""
        if not self.allow_chaos_kill:
            logger.warning("chaos kill refused: raylet is driver-embedded")
            return False
        logger.warning("raylet dying on chaos request")
        threading.Thread(target=lambda: (time.sleep(0.1), os._exit(1)),
                         daemon=True).start()
        return True

    # ------------------------------------------------------------ scheduling
    def rpc_submit_task(self, conn, req_id, payload):
        spec: TaskSpec = payload["spec"]
        self._submit(spec, payload.get("spillback_count", 0))
        return True

    def _submit(self, spec: TaskSpec, spillback_count: int) -> None:
        qt = _QueuedTask(spec, spillback_count)
        if spec.trace_ctx is not None:
            qt.queued_us = tracing.now_us()
        with self._lock:
            self._queue.append(qt)
            # Deep-queue regime: a FIFO submission behind >SCAN_MAX blocked
            # tickets cannot dispatch before them, and every event that
            # frees capacity (task done, worker ready, resource update)
            # calls _schedule itself — so skip the per-submit scan and keep
            # submission O(1) under a 20k-task burst (envelope phase 1).
            deep = len(self._queue) > self._SCHED_SCAN_BLOCKED_MAX
            if not deep:
                # Demand-driven prestart (reference PrestartWorkers,
                # worker_pool.cc:1363): keep ~1 worker/CPU booting ahead of
                # the dispatch pass so a burst's first wave doesn't pay a
                # worker boot inline. Dedup against idle here, against
                # in-flight starts in the pool; O(1) per submit, and the
                # deep regime skips it (demand is already saturated).
                ekey = _env_key(spec.runtime_env)
                idle = len(self._idle_pools.get(ekey) or ())
                target = self._worker_pool.prestart_target(
                    len(self._queue), ekey)
                if target > idle:
                    self._worker_pool.request(
                        ekey, spec.runtime_env, target, kind="prestart")
        if not deep:
            self._schedule()

    def _assign_tpus(self, amount: float) -> Optional[List[int]]:
        """Caller holds self._lock. Returns chip indices for `amount` TPU
        (whole chips for integer demands; one shared chip for fractions),
        or None when accounting says yes but no slot fits (fragmentation —
        fall back to unindexed execution rather than deadlock)."""
        if amount <= 0 or not self._tpu_slots:
            return []
        if amount < 1.0:
            # best fit: the most-used slot that still has room
            best = None
            for i, rem in self._tpu_slots.items():
                if rem >= amount and (best is None
                                      or rem < self._tpu_slots[best]):
                    best = i
            if best is None:
                return None
            self._tpu_slots[best] -= amount
            return [best]
        need = int(amount)
        free = [i for i, rem in self._tpu_slots.items() if rem >= 1.0]
        if len(free) < need:
            return None
        for i in free[:need]:
            self._tpu_slots[i] = 0.0
        return free[:need]

    def _release_tpus(self, ids: Optional[List[int]], amount: float) -> None:
        if not ids:
            return
        with self._lock:
            if amount < 1.0:
                self._tpu_slots[ids[0]] = min(
                    1.0, self._tpu_slots.get(ids[0], 0.0) + amount)
            else:
                for i in ids:
                    self._tpu_slots[i] = 1.0

    # Bounded scheduling scan: _schedule runs on every task completion, so
    # an unbounded drain is O(queue) work per completion — O(n^2) for a
    # deep queue (the r05 envelope's 10k-task phase measured ~5 tasks/s and
    # the lock hold starved heartbeats until the GCS declared the node
    # dead). After this many non-dispatchable tickets the pass stops and
    # the remainder stays queued untouched — bounded work per completion,
    # at worst a window of head-of-line blocking for heterogeneous demands
    # (the reference's LocalTaskManager caps its dispatch scans the same
    # way).
    _SCHED_SCAN_BLOCKED_MAX = 256

    def _schedule(self) -> None:
        """Drain the queue: dispatch locally or spill to a better node.

        Mirrors ClusterTaskManager::QueueAndScheduleTask + LocalTaskManager
        dispatch (`cluster_task_manager.cc:44,418`).
        """
        dispatched_any = False
        spawn_wants: Dict[Optional[str], list] = {}  # env_key -> [count, env]
        with self._lock:
            pending: deque[_QueuedTask] = deque()
            blocked = 0
            while self._queue:
                if blocked >= self._SCHED_SCAN_BLOCKED_MAX:
                    break
                qt = self._queue.popleft()
                spec = qt.spec
                demand = self._effective_demand(spec)
                target = self._choose_node(spec, qt.spillback_count)
                if target is None:
                    # infeasible anywhere right now — keep queued
                    pending.append(qt)
                    blocked += 1
                    continue
                if target != self.node_id.hex():
                    if not self._spill_to(target, qt):
                        pending.append(qt)
                        blocked += 1
                    continue
                if not self._resources_ok(spec, demand):
                    pending.append(qt)
                    blocked += 1
                    continue
                ekey = _env_key(spec.runtime_env)
                if ekey is not None:
                    env_err = self._env_manager.creation_error(ekey)
                    if env_err is not None:
                        self._notify_owner_task_failed(spec, env_err)
                        continue
                handle = self._acquire_worker(ekey)
                if handle is None:
                    pending.append(qt)
                    blocked += 1
                    w = spawn_wants.setdefault(ekey, [0, spec.runtime_env])
                    w[0] += 1
                    continue
                self._charge_resources(spec, demand)
                handle.current_task = spec
                handle.task_started = time.monotonic()
                tpu_amount = demand.get("TPU", 0.0)
                tpu_ids = self._assign_tpus(tpu_amount)
                handle.tpu_grant = (tpu_ids, tpu_amount)
                push_payload = {"spec": spec, "tpu_ids": tpu_ids or []}
                if spec.trace_ctx is not None and qt.queued_us:
                    # lease span: queue-arrival -> worker grant, parented
                    # under the submitter's span; dispatch_us lets the
                    # executor open its dispatch span where the lease ends
                    # (push-to-run gap = worker wakeup + arg resolution)
                    t_now = tracing.now_us()
                    tracing.add_complete(
                        f"lease::{spec.method_name}", "task_lease",
                        qt.queued_us, t_now - qt.queued_us,
                        trace_id=spec.trace_ctx[0],
                        parent_id=spec.trace_ctx[1],
                        task_id=spec.task_id.binary().hex(),
                        node_id=self.node_id.hex())
                    push_payload["dispatch_us"] = t_now
                handle.conn.push("execute_task", push_payload)
                dispatched_any = True
            if self._queue:
                # Early break with an unexamined tail: the blocked head
                # tickets rotate BEHIND the tail, so successive passes walk
                # the whole queue round-robin — a task behind 256 blocked
                # tickets is examined on the next pass instead of starving
                # behind the same head forever.
                self._queue.extend(pending)
            else:
                self._queue = pending
            for ekey, (count, renv) in spawn_wants.items():
                self._maybe_spawn(ekey, renv, needed=count)
        if dispatched_any:
            self._report_resources()

    def _effective_demand(self, spec: TaskSpec) -> Dict[str, float]:
        demand = dict(spec.resources)
        if not demand and spec.task_type == TaskType.NORMAL:
            demand = {"CPU": 1.0}
        return demand

    def _choose_node(self, spec: TaskSpec, spillback_count: int) -> Optional[str]:
        """Returns node hex id, possibly self; None if infeasible."""
        if spillback_count >= 1 or spec.scheduling.placement_group_id is not None:
            # spilled tasks run where they land if feasible; PG tasks were
            # routed to the bundle's node already
            return self.node_id.hex()
        demand = self._effective_demand(spec)
        views = [NodeView(self.node_id.binary(), self.resources_total,
                          self.resources_available, self.labels)]
        addr_by_hex = {self.node_id.hex(): self._server.address}
        for hexid, v in self._cluster_view.items():
            if not v.get("alive", True) or v.get("quarantined"):
                # quarantined: alive but degraded — takes no NEW dispatch
                continue
            views.append(NodeView(bytes.fromhex(hexid), v["total"], v["available"], v.get("labels", {})))
            addr_by_hex[hexid] = v["address"]
        chosen = self._policy.select_node(views, demand, spec.scheduling,
                                          prefer_node=self.node_id.binary())
        if chosen is None:
            return None
        return chosen.hex()

    def _spill_to(self, target_hex: str, qt: _QueuedTask) -> bool:
        v = self._cluster_view.get(target_hex)
        if v is None:
            return False
        try:
            peer = self._peer(v["address"])
            peer.notify("submit_task", {"spec": qt.spec, "spillback_count": qt.spillback_count + 1})
            # Tell the owner where its task went (best-effort): a spilled
            # task can only reach one hop, so this is its node of record —
            # if that whole node later dies (raylet included), the owner's
            # node-death failover is the only surviving signal.
            try:
                self._peer(qt.spec.owner_address).notify("task_spilled", {
                    "task_id": qt.spec.task_id,
                    "node_id": bytes.fromhex(target_hex)})
            except Exception:
                logger.debug("task_spilled notify to owner lost",
                             exc_info=True)
            return True
        except Exception:
            # Mark the target suspect so we do not deterministically re-pick
            # it while the GCS death notice is still in flight.
            logger.warning("spillback to %s failed; marking node suspect", target_hex[:8])
            v["alive"] = False
            return False

    def _resources_ok(self, spec: TaskSpec, demand: Dict[str, float]) -> bool:
        pg = spec.scheduling.placement_group_id
        if pg is not None:
            key = (pg, max(spec.scheduling.bundle_index, 0))
            pool = self._bundles.get(key)
            if pool is None:
                return False
            return all(pool.get(r, 0.0) + 1e-9 >= q for r, q in demand.items())
        return all(self.resources_available.get(r, 0.0) + 1e-9 >= q for r, q in demand.items())

    def _charge_resources(self, spec: TaskSpec, demand: Dict[str, float]) -> None:
        pg = spec.scheduling.placement_group_id
        pool = self.resources_available
        if pg is not None:
            pool = self._bundles[(pg, max(spec.scheduling.bundle_index, 0))]
        for r, q in demand.items():
            pool[r] = pool.get(r, 0.0) - q

    def _release_resources(self, spec: TaskSpec) -> None:
        demand = self._effective_demand(spec)
        with self._lock:
            pg = spec.scheduling.placement_group_id
            pool = self.resources_available
            if pg is not None:
                key = (pg, max(spec.scheduling.bundle_index, 0))
                pool = self._bundles.get(key)
                if pool is None:
                    return
            for r, q in demand.items():
                pool[r] = pool.get(r, 0.0) + q

    def _acquire_worker(self, env_key: Optional[str] = None
                        ) -> Optional[WorkerHandle]:
        """Pop an idle worker from the matching runtime-env pool: O(1) per
        dispatch (plus skipped dead connections) instead of a linear scan
        over every idle worker of every env on a busy mixed-env node."""
        pool = self._idle_pools.get(env_key)
        while pool:
            wid = pool.popleft()
            w = self._workers.get(wid)
            if w is None or not w.conn.alive:
                continue  # raced a disconnect; entry already stale
            return w
        if pool is not None and not pool:
            self._idle_pools.pop(env_key, None)  # drop drained env pools
        return None

    def _starting_for(self, env_key: Optional[str]) -> int:
        return sum(1 for p in self._starting
                   if self._starting_env.get(p.pid) == env_key)

    # ------------------------------------------------- worker-pool surface
    # Thread-safe accessors for the WorkerPool (its serve thread runs
    # outside the raylet lock; everything below takes it).
    def _spawn_inflight(self, env_key: Optional[str]) -> int:
        with self._lock:
            return self._starting_for(env_key)

    def _starting_count(self) -> int:
        with self._lock:
            return len(self._starting)

    def _has_workers_for(self, env_key: Optional[str]) -> bool:
        with self._lock:
            return any(w.env_key == env_key and not w.is_driver
                       for w in self._workers.values())

    def _idle_count(self, env_key: Optional[str]) -> int:
        with self._lock:
            pool = self._idle_pools.get(env_key)
            return len(pool) if pool else 0

    def _task_worker_count(self, env_key: Optional[str]) -> int:
        """Live task-capable (non-driver, non-actor) workers of an env —
        busy OR idle. The prestart policy dedups against this: a busy
        worker still occupies its CPU, so prestarting 'replacements' for
        busy workers just forks an unbounded stream of idlers."""
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if not w.is_driver and w.actor_id is None
                       and w.env_key == env_key)

    def _live_demand(self, env_key: Optional[str]) -> int:
        """Workers this env could consume RIGHT NOW: pending actor specs
        (one dedicated worker each) plus queued tasks that are dispatchable
        under CURRENT resources (cumulatively simulated over a bounded
        scan). Counting every queued task would let a stale spawn request
        fork for tasks that have no CPU to run on — the per-completion
        release->handoff window makes such requests a steady drip under a
        deep queue."""
        from itertools import islice

        with self._lock:
            n = sum(1 for s in self._pending_actor_specs
                    if _env_key(s.runtime_env) == env_key)
            avail = dict(self.resources_available)
            bundle_avail: Dict[Tuple, Dict[str, float]] = {}
            for qt in islice(self._queue, 512):
                spec = qt.spec
                if _env_key(spec.runtime_env) != env_key:
                    continue
                demand = self._effective_demand(spec)
                pg = spec.scheduling.placement_group_id
                if pg is not None:
                    # PG tasks charge their bundle, not the node pool —
                    # simulated cumulatively too, else 64 queued tasks on a
                    # 1-CPU bundle all count as live demand
                    key = (pg, max(spec.scheduling.bundle_index, 0))
                    pool = bundle_avail.get(key)
                    if pool is None:
                        src = self._bundles.get(key)
                        if src is None:
                            continue
                        pool = bundle_avail[key] = dict(src)
                else:
                    pool = avail
                if all(pool.get(r, 0.0) + 1e-9 >= q
                       for r, q in demand.items()):
                    for r, q in demand.items():
                        pool[r] = pool.get(r, 0.0) - q
                    n += 1
            return n

    def _adopt_forked(self, pid: int, env_key: Optional[str]) -> None:
        """A template just forked worker `pid` for us: thread it into the
        startup pipeline exactly like a cold Popen (same registration
        adoption, same reaper poll, same spawn-lease refcount). Handles the
        race where the child registered before the fork reply was read."""
        from ray_tpu.core.worker_pool import ForkedWorkerProc

        shim = ForkedWorkerProc(pid)
        with self._lock:
            # a NEW fork with pid P proves any older _starting entry for P
            # is dead (live pids are unique) — drop it now or the pid-keyed
            # _starting_env entry is overwritten and one env lease leaks
            stale = [p for p in self._starting if p.pid == pid]
            for p in stale:
                self._starting.remove(p)
            stale_env = self._starting_env.pop(pid, None) if stale else None
        if stale_env is not None:
            self._env_manager.release(stale_env)
        if env_key is not None:
            # spawn LEASE, mirroring the cold path: hold the env's refcount
            # until the worker registers (takes its own) or dies booting.
            # Taken BEFORE the shim is visible in _starting so registration
            # can never release it first (flock IO stays off the raylet
            # lock, same as the cold path).
            self._env_manager.acquire(env_key)
        with self._lock:
            raced = None
            for w in self._workers.values():
                if w.pid == pid:
                    # raced its own registration: it already took its env
                    # ref there; just give the handle a killable proc
                    raced = w
                    break
            if raced is None:
                self._starting.append(shim)
                if env_key is not None:
                    self._starting_env[pid] = env_key
                return
            if raced.proc is None:
                raced.proc = shim
        if env_key is not None:
            self._env_manager.release(env_key)  # return the unused lease

    def _maybe_spawn(self, env_key: Optional[str] = None,
                     runtime_env: Optional[dict] = None,
                     needed: int = 1) -> None:
        """Ask the warm pool to bring this env's worker count up to
        `needed` (an absolute backlog figure — the pool dedups against
        in-flight starts, so every scheduling pass during a worker's boot
        re-arming with the same count cannot overspawn). The pool serves
        it with template forks when it can, cold Popen spawns (bounded by
        maximum_startup_concurrency) when it can't."""
        if env_key is not None and \
                self._env_manager.creation_error(env_key) is not None:
            return  # creation already failed; don't respawn forever
        self._worker_pool.request(env_key, runtime_env, needed)

    def rpc_task_done(self, conn, req_id, payload):
        wid: WorkerID = payload["worker_id"]
        retiring = bool(payload.get("retiring"))
        with self._lock:
            w = self._workers.get(wid)
            if w is None:
                return True
            spec = w.current_task
            w.current_task = None
            grant, w.tpu_grant = w.tpu_grant, None
            if spec is not None:
                w.recent_done.append(
                    (spec.task_id, spec.owner_address, time.monotonic()))
            if retiring:
                # max_calls recycling: the worker exits after this notify.
                # Drop it NOW so no task is dispatched into the closing
                # process, and so its disconnect reads as clean (reference
                # worker_pool DisconnectWorker on max-calls exit).
                self._workers.pop(wid, None)
        if spec is not None:
            self._release_resources(spec)
        if grant is not None:
            self._release_tpus(*grant)
        if retiring:
            if w.env_key:
                self._env_manager.release(w.env_key)
            # A retiring worker drains its ResultBuffer before os._exit, but
            # that final drain can fail against a transiently-down owner and
            # the clean pop above means no disconnect failover will fire.
            # After a grace exceeding the drain's WORST case (per-owner 2s
            # short-timeout reconnect plus the 5s in-flight wait — firing
            # mid-drain would spuriously retry a task that succeeded), send
            # the idempotent failover anyway: owners that got their results
            # no-op, an owner that lost them unsticks.
            entries = list(w.recent_done)
            if entries:
                grace = 10.0
                t = threading.Timer(
                    grace, lambda: self._failover_recent_done(
                        entries, extra_window=grace))
                t.daemon = True
                t.start()
            self._schedule()
            self._report_resources()
            return True
        # Completion fast lane: hand the next queued same-env task straight
        # to the just-freed worker. When the handoff consumed exactly what
        # the finished task released (the homogeneous deep-queue regime) no
        # other ticket became dispatchable, so the full _schedule() pass —
        # O(blocked-scan) policy evaluations per completion — is skipped.
        handed = self._try_handoff(w)
        if handed is not None and spec is not None and \
                self._effective_demand(spec) == self._effective_demand(handed) \
                and self._pool_key(spec) == self._pool_key(handed):
            # the handoff re-charged exactly the pool the finished task
            # released into: no other ticket became dispatchable
            self._report_resources()
            return True
        if handed is None:
            with self._lock:
                if w.actor_id is None and w.conn.alive:
                    # a pending actor spec of this env takes the worker
                    # before it pools: only fresh registrations claimed
                    # specs before, so a spec could coexist with an idle
                    # same-env worker forever (the warm pool's demand
                    # dedup counts that idle worker and spawns nothing)
                    if self._claim_pending_actor_spec(w) is None:
                        w.idle_since = time.monotonic()
                        self._idle_pools.setdefault(
                            w.env_key, deque()).append(wid)
        self._schedule()
        self._report_resources()
        return True

    @staticmethod
    def _pool_key(spec: TaskSpec):
        """Identity of the resource pool a task charges: None for the node
        pool, (pg_id, bundle) for a placement-group bundle. The handoff may
        only skip the full _schedule() pass when release and re-charge hit
        the SAME pool — equal demand dicts against different pools still
        leave freed capacity behind."""
        pg = spec.scheduling.placement_group_id
        return None if pg is None else (pg, max(spec.scheduling.bundle_index, 0))

    def _try_handoff(self, w: WorkerHandle) -> Optional[TaskSpec]:
        """Dispatch the HEAD queued task into the just-freed worker without
        a full _schedule() scan. Returns the dispatched spec, or None when
        the head needs anything the fast lane can't do (another env's pool,
        spilling to a peer, a spawn, infeasible resources) — then the caller
        falls back to the full pass, so behavior degrades to the old path
        rather than diverging from it."""
        with self._lock:
            # Liveness re-checked UNDER the lock: _on_worker_disconnect
            # serializes on it, so a worker whose disconnect already ran
            # (popped from _workers, current_task seen as None — nobody
            # would ever fail the task over) can't receive a dispatch here.
            if (w.actor_id is not None or not w.conn.alive
                    or self._workers.get(w.worker_id) is not w):
                return None
            if not self._queue:
                return None
            qt = self._queue[0]
            spec = qt.spec
            if _env_key(spec.runtime_env) != w.env_key:
                return None
            if w.env_key is not None and \
                    self._env_manager.creation_error(w.env_key) is not None:
                return None
            demand = self._effective_demand(spec)
            if not self._resources_ok(spec, demand):
                return None
            if self._choose_node(spec, qt.spillback_count) != self.node_id.hex():
                return None  # wants another node: let _schedule spill it
            self._queue.popleft()
            self._charge_resources(spec, demand)
            w.current_task = spec
            w.task_started = time.monotonic()
            tpu_amount = demand.get("TPU", 0.0)
            tpu_ids = self._assign_tpus(tpu_amount)
            w.tpu_grant = (tpu_ids, tpu_amount)
            w.conn.push("execute_task", {
                "spec": spec, "tpu_ids": tpu_ids or []})
            return spec

    # ---------------------------------------------------------------- actors
    def rpc_create_actor(self, conn, req_id, payload):
        """Push from GCS: lease a dedicated worker and instantiate."""
        spec = payload["spec"]
        ekey = _env_key(spec.runtime_env)
        if ekey is not None:
            env_err = self._env_manager.creation_error(ekey)
            if env_err is not None:
                self._gcs.notify("actor_failed", {
                    "actor_id": spec.actor_id, "reason": env_err,
                    "node_id": self.node_id.binary()})
                return True
        with self._lock:
            handle = self._acquire_worker(ekey)
            if handle is None:
                self._pending_actor_specs.append(spec)
                needed = sum(1 for s in self._pending_actor_specs
                             if _env_key(s.runtime_env) == ekey)
                self._maybe_spawn(ekey, spec.runtime_env, needed=needed)
                return True
            self._assign_actor(handle, spec)
        return True

    def _claim_pending_actor_spec(self, handle: WorkerHandle):
        """Caller holds self._lock. Hand the worker a pending actor spec of
        its runtime-env pool (assigning it as the actor) — the ONE claim
        policy shared by fresh registrations and workers going idle.
        Returns the claimed spec, or None."""
        for s in self._pending_actor_specs:
            if _env_key(s.runtime_env) == handle.env_key:
                self._pending_actor_specs.remove(s)
                self._assign_actor(handle, s)
                return s
        return None

    def _assign_actor(self, handle: WorkerHandle, spec) -> None:
        handle.actor_id = spec.actor_id
        # charge actor resources against the node (held for actor lifetime,
        # released on worker death/kill via _release_actor_charge)
        demand = dict(spec.resources)
        pg = spec.scheduling.placement_group_id
        key = None
        if pg is not None:
            key = (pg, max(spec.scheduling.bundle_index, 0))
            pool = self._bundles.get(key)
            if pool is None:
                key = None
                pool = self.resources_available
        else:
            pool = self.resources_available
        for r, q in demand.items():
            pool[r] = pool.get(r, 0.0) - q
        handle.actor_charge = (key, demand)
        tpu_amount = demand.get("TPU", 0.0)
        tpu_ids = self._assign_tpus(tpu_amount)
        handle.tpu_grant = (tpu_ids, tpu_amount)
        handle.conn.push("become_actor", {
            "spec": spec, "tpu_ids": tpu_ids or [],
            # the incarnation this worker instantiates (GCS-stamped at
            # dispatch): its replies carry it, fence checks compare to it
            "incarnation": getattr(spec, "incarnation", 0)})

    def _release_actor_charge(self, handle: WorkerHandle) -> None:
        charge = handle.actor_charge
        if charge is None:
            return
        handle.actor_charge = None
        if handle.tpu_grant is not None:
            self._release_tpus(*handle.tpu_grant)
            handle.tpu_grant = None
        key, demand = charge
        with self._lock:
            pool = self._bundles.get(key) if key is not None else self.resources_available
            if pool is None:
                return
            for r, q in demand.items():
                pool[r] = pool.get(r, 0.0) + q
        self._report_resources()

    def rpc_kill_actor_worker(self, conn, req_id, payload):
        actor_id = payload["actor_id"]
        with self._lock:
            target = None
            for w in self._workers.values():
                if w.actor_id == actor_id:
                    target = w
                    break
        if target is not None:
            target.actor_id = None  # suppress actor_failed report: this is a kill
            if target.proc is not None:
                try:
                    target.proc.kill()
                except (OSError, ProcessLookupError):
                    pass  # already exited
            else:
                try:
                    target.conn.push("exit", {})
                except (OSError, RuntimeError):
                    pass  # worker link already down; reaper will SIGKILL
        return True

    # ------------------------------------------------------------- placement
    def rpc_prepare_bundle(self, conn, req_id, payload):
        key = (payload["pg_id"], payload["bundle_index"])
        resources = payload["resources"]
        with self._lock:
            if key in self._bundles:
                # Idempotent re-prepare: a replacement head resuming an
                # interrupted 2-phase creation (or a client retry of the
                # create RPC) re-sends prepares the old head already made;
                # the reservation is held — re-charging it would leak. The
                # prepare clock RESTARTS (a creation is actively in flight
                # again — the orphan reaper must not fire mid-resume).
                if not self._bundles_committed.get(key):
                    self._bundle_prepared_at[key] = time.monotonic()
                return True
            if not all(self.resources_available.get(r, 0.0) + 1e-9 >= q
                       for r, q in resources.items()):
                return False
            for r, q in resources.items():
                self.resources_available[r] = self.resources_available.get(r, 0.0) - q
            self._bundles[key] = dict(resources)
            self._bundle_reservations[key] = dict(resources)
            self._bundles_committed[key] = False
            self._bundle_prepared_at[key] = time.monotonic()
        self._report_resources()
        return True

    def rpc_commit_bundle(self, conn, req_id, payload):
        key = (payload["pg_id"], payload["bundle_index"])
        with self._lock:
            self._bundles_committed[key] = True
            self._bundle_prepared_at.pop(key, None)
        return True

    def rpc_return_bundle(self, conn, req_id, payload):
        key = (payload["pg_id"], payload["bundle_index"])
        with self._lock:
            pool = self._bundles.pop(key, None)
            self._bundles_committed.pop(key, None)
            self._bundle_reservations.pop(key, None)
            self._bundle_prepared_at.pop(key, None)
            if pool is None:
                return True
            # return the bundle's original reservation to the node
            # (anything still charged inside the bundle is leaked by the
            # caller's contract: PG removal implies its tasks are done)
        # recompute: original reservation minus what's still charged = pool
        # we return the *full* original amount; find it from payload if given
        resources = payload.get("resources")
        with self._lock:
            if resources is None:
                resources = pool
            for r, q in resources.items():
                self.resources_available[r] = self.resources_available.get(r, 0.0) + q
        self._report_resources()
        return True

    # ------------------------------------------------------------ object plane
    def rpc_obj_create(self, conn, req_id, payload):
        """Worker asks to allocate a segment it will write directly
        (file segments via writev — see _put_to_store; the reply's
        `recycled` flag reports whether the reuse pool served it, mostly
        for tests/diagnostics: a recycled segment's hot pages make the
        write run at memory bandwidth)."""
        object_id, size = payload["object_id"], payload["size"]
        info: dict = {}
        try:
            shm = self.store.create(object_id, size, info=info)
            name = shm.name
            shm.close()
            jid = payload.get("job_id")
            if jid is not None:
                # job attribution of the primary copy: a dead job's reap
                # deletes its objects by this index
                with self._lock:
                    self._obj_jobs[object_id] = jid
            return {"ok": True, "name": name,
                    "recycled": info.get("recycled", False)}
        except FileExistsError:
            return {"ok": False, "exists": True}
        except ObjectStoreFullError as e:
            # typed backpressure: the WORKER bounds its retry window
            # (put_full_timeout_s) — this handler runs on the rpc loop and
            # must not block on headroom itself. `fatal` short-circuits the
            # retry loop for objects that can never fit.
            return {"ok": False, "full": True,
                    "degraded": self.store.stats()["spill_degraded"],
                    "fatal": size > self.store.capacity,
                    "error": str(e)}

    def rpc_obj_seal(self, conn, req_id, payload):
        """Fire-and-forget on the put hot path (the single-writer seal
        piggybacks on the same ordered connection as obj_create, so a
        blocking round-trip buys nothing)."""
        self.store.seal(payload["object_id"])
        self._resolve_pulls(payload["object_id"])
        return True

    def rpc_obj_pin(self, conn, req_id, payload):
        """Pin a local sealed object for a zero-copy reader; reply is the
        authoritative (segment_name, size) or None. Issued as a CALL
        pipelined with the reader's optimistic attach: the reader only
        trusts its views once this reply confirms the name it attached —
        which makes segment recycling safe (a recycled inode can't match).
        Pins are tracked per connection and reaped if the reader dies."""
        loc = self.store.pin(payload["object_id"])
        if loc is not None:
            self._track_pin(conn, payload["object_id"])
        return loc

    def rpc_obj_unpin(self, conn, req_id, payload):
        """Notify: a reader's last view over the segment was GC'd (or its
        optimistic attach failed and this is the compensating release)."""
        oid = payload["object_id"]
        key = id(conn) if conn is not None else None
        with self._lock:
            m = self._conn_pins.get(key)
            if m is None or oid not in m:
                return True  # pin never landed (or already reaped): no-op
            m[oid] -= 1
            if m[oid] <= 0:
                m.pop(oid, None)
        self.store.unpin(oid)
        return True

    def _track_pin(self, conn, oid) -> None:
        key = id(conn) if conn is not None else None
        with self._lock:
            m = self._conn_pins.get(key)
            if m is None:
                m = self._conn_pins[key] = {}
                if conn is not None:
                    conn.on_close.append(
                        lambda c, k=key: self._reap_conn_pins(k))
            m[oid] = m.get(oid, 0) + 1
        if conn is not None and not getattr(conn, "alive", True):
            # the connection may have closed BEFORE our on_close append —
            # its callbacks already ran and will never fire again (a pin
            # taken for a deferred pull reply whose requester crashed
            # mid-pull). Reap now; _reap_conn_pins pops the map under the
            # lock, so racing with a late callback is idempotent.
            self._reap_conn_pins(key)

    def _reap_conn_pins(self, key: int) -> None:
        """A pinning reader's connection died: release everything it held
        (reference: plasma client disconnect releases its refs)."""
        with self._lock:
            m = self._conn_pins.pop(key, None)
        if not m:
            return
        for oid, count in m.items():
            for _ in range(count):
                self.store.unpin(oid)
        logger.debug("reaped %d pins from dead reader connection",
                     sum(m.values()))

    def rpc_obj_put_bytes(self, conn, req_id, payload):
        object_id = payload["object_id"]
        try:
            self.store.put_bytes(object_id, payload["data"])
        except FileExistsError:
            pass
        except ObjectStoreFullError as e:
            return {"ok": False, "full": True,
                    "degraded": self.store.stats()["spill_degraded"],
                    "fatal": len(payload["data"]) > self.store.capacity,
                    "error": str(e)}
        self._resolve_pulls(object_id)
        return True

    def rpc_obj_lookup(self, conn, req_id, payload):
        return self.store.lookup(payload["object_id"])

    def rpc_obj_delete(self, conn, req_id, payload):
        with self._lock:
            self._obj_jobs.pop(payload["object_id"], None)
        self.store.delete(payload["object_id"])
        # a pull parked on the (now unreachable) seal must not hang
        self._resolve_pulls(payload["object_id"], "object deleted")
        return True

    def rpc_obj_stats(self, conn, req_id, payload):
        return self.store.stats()

    def rpc_fetch_object(self, conn, req_id, payload):
        """Peer raylet requests the object bytes (single-shot transfer;
        small-object fast path — big objects go through the chunk RPCs).
        The copy into the reply frame is the wire's — read_bytes rides a
        pinned view, no extra staging."""
        data = self.store.read_bytes(payload["object_id"])
        return data  # None if not here

    def rpc_fetch_object_meta(self, conn, req_id, payload):
        """Size probe before a chunked pull (cf. reference object directory);
        carries the data-plane address so the puller can ride raw sockets."""
        loc = self.store.lookup(payload["object_id"])
        if loc is None:
            return None
        return {"size": loc[1], "data_addr": self._data_plane.address,
                "segment": loc[0], "hostname": _socket_mod.gethostname()}

    def rpc_data_plane_addr(self, conn, req_id, payload):
        return self._data_plane.address

    def rpc_fetch_object_chunk(self, conn, req_id, payload):
        """Serve one bounded slice of a sealed object, read straight out of
        the shm segment — the sender never materializes the whole object
        (reference ObjectBufferPool chunk reads, object_manager.proto:61).
        Pinned for the read so memory pressure can't spill the segment
        between a peer's chunks (each spill would cost a full restore)."""
        with self.store.pinned_view(payload["object_id"]) as buf:
            if buf is None:
                return None
            off = payload["offset"]
            ln = payload["length"]
            return bytes(buf.view[off:off + ln])

    def rpc_pull_object(self, conn, req_id, payload):
        """Worker asks: make object local, reply (name,size) when done.

        `source` is the raylet address believed to hold a copy (from the
        owner's location table, cf. OwnershipBasedObjectDirectory).
        """
        object_id: ObjectID = payload["object_id"]
        pin = bool(payload.get("pin"))
        if pin:
            loc, reason = self.store.pin_ex(object_id)
            if loc is not None:
                self._track_pin(conn, object_id)
                return loc
            if reason == "pin_cap":
                # resident, but indefinite reader pins are at the
                # max_pinned_fraction cap: grant a TRANSIENT pin with a
                # copy-only marker — the reader copies out inside a bounded
                # window and unpins, instead of wedging the store (or
                # spuriously reporting the object lost)
                loc = self.store.pin(object_id, transient=True)
                if loc is not None:
                    self._track_pin(conn, object_id)
                    return (loc[0], loc[1], "copy_only")
        else:
            loc = self.store.lookup(object_id)
            if loc is not None:
                return loc
        with self._lock:
            waiters = self._pending_pulls.setdefault(object_id, [])
            waiters.append((conn, req_id, pin))
            first = len(waiters) == 1
        if first:
            t = threading.Thread(
                target=self._do_pull, args=(object_id, payload.get("source")),
                daemon=True)
            t.start()
        return rpc.RpcServer.DEFERRED

    def _do_pull(self, object_id: ObjectID, source: Optional[str]) -> None:
        err = None
        try:
            if source and source != self._server.address:
                peer = self._peer(source)
                cfg = get_config()
                chunk = cfg.object_transfer_chunk_size_bytes
                meta = peer.call("fetch_object_meta", {"object_id": object_id},
                                 timeout=30)
                if meta is None:
                    err = f"object {object_id} not found at {source}"
                elif self._try_adopt_local(object_id, meta, peer):
                    pass  # same-host kernel-side copy succeeded
                elif meta["size"] <= chunk:
                    # small objects NEVER wait on the pull budget: a 2 MiB
                    # fetch queuing FIFO behind a multi-GiB admission ticket
                    # would turn milliseconds into tens of seconds
                    data = peer.call("fetch_object", {"object_id": object_id},
                                     timeout=cfg.object_transfer_chunk_timeout_s)
                    if data is not None:
                        try:
                            # bounded wait for headroom: this thread may
                            # block, the rpc loop does not
                            self.store.put_bytes(
                                object_id, data,
                                timeout_s=min(cfg.put_full_timeout_s, 5.0))
                        except FileExistsError:
                            pass
                        except ObjectStoreFullError as e:
                            err = f"pull target store full: {e}"
                    else:
                        err = f"object {object_id} not found at {source}"
                else:
                    err = self._pull_chunked(peer, object_id, meta["size"],
                                             meta.get("data_addr"))
            else:
                # source is THIS raylet (or unknown) and lookup missed: a
                # local producer may have created-but-not-yet-sealed the
                # segment (seal is a fire-and-forget notify on the put fast
                # path) — wait for the seal, BOUNDED so a writer that died
                # mid-put can't park the waiters forever.
                if self.store.status(object_id) == "unsealed":
                    deadline = (time.monotonic()
                                + get_config().object_transfer_chunk_timeout_s)
                    while time.monotonic() < deadline:
                        if self.store.status(object_id) != "unsealed":
                            break
                        with self._lock:
                            if object_id not in self._pending_pulls:
                                return  # seal/delete already resolved them
                        time.sleep(0.05)
                    if self.store.contains(object_id):
                        self._resolve_pulls(object_id)
                        return
                    err = f"object {object_id} was created but never sealed"
                else:
                    err = f"no source for object {object_id}"
        except Exception as e:
            err = f"pull failed: {e}"
        self._resolve_pulls(object_id, err)

    def _try_adopt_local(self, object_id: ObjectID, meta: dict,
                         peer: rpc.RpcClient) -> bool:
        """Same-host fast path: the source raylet shares this machine's
        /dev/shm, so 'transfer' is a kernel-side copy_file_range of the
        segment file (no sockets, no fault-zeroing). False → fall through
        to the data-plane/RPC pull paths."""
        seg = meta.get("segment")
        if (not seg or seg.startswith("@")
                or meta.get("hostname") != _socket_mod.gethostname()):
            return False  # cheap rejections BEFORE touching the pull budget
        size = meta["size"]
        # small copies are instant — admission control only gates sizes that
        # could meaningfully overcommit store memory
        gate = size > get_config().object_transfer_chunk_size_bytes
        if gate:
            self._pull_budget.acquire(size)
        try:
            ok = self.store.adopt_local_copy(object_id, seg, size)
            if ok and not self._adopt_source_stable(peer, object_id, seg):
                # the source store may RECYCLE a deleted segment's inode
                # (reuse pool) — an adopt that raced the delete could have
                # copied overwritten bytes. The source re-confirming the
                # same (object, segment) AFTER our copy proves the entry
                # was live for the whole window; otherwise discard.
                self.store.delete(object_id)
                return False
            return ok
        except FileExistsError:
            return False  # concurrent materialization: chunked path waits on it
        except Exception:
            logger.warning("same-host adopt of %s failed; falling back",
                           object_id, exc_info=True)
            return False
        finally:
            if gate:
                self._pull_budget.release(size)

    @staticmethod
    def _adopt_source_stable(peer: rpc.RpcClient, object_id: ObjectID,
                             seg: str) -> bool:
        """Post-copy verification for the same-host adopt fast path: the
        source still holds `object_id` in the SAME segment AFTER our
        kernel-side copy. True means no delete (and so no inode recycle)
        could have raced the copy window."""
        try:
            meta = peer.call("fetch_object_meta", {"object_id": object_id},
                             timeout=10)
        except Exception:
            return False
        return meta is not None and meta.get("segment") == seg

    def _pull_chunked(self, peer: rpc.RpcClient, object_id: ObjectID,
                      size: int, data_addr: Optional[str] = None) -> Optional[str]:
        """Materialize a big object directly into a pre-created shm segment,
        sealing when complete (reference ObjectManager chunk pulls) — peak
        extra memory is bounded, never 2x the object. Preferred path: striped
        raw-socket fetch over the peer's data plane (shm->kernel->shm, no
        serialization); fallback: pipelined RPC chunks.

        Returns an error string, or None on success."""
        cfg = get_config()
        self._pull_budget.acquire(size)
        try:
            try:
                shm = self.store.create_blocking(
                    object_id, size, min(cfg.put_full_timeout_s, 5.0))
            except ObjectStoreFullError as e:
                return f"pull target store full: {e}"
            except FileExistsError:
                # A local producer (e.g. lineage re-execution) or another pull
                # beat us to the entry — but it may be UNSEALED; report success
                # only once it seals, else waiters get a spurious lost-object.
                deadline = time.monotonic() + cfg.object_transfer_chunk_timeout_s
                while time.monotonic() < deadline:
                    if self.store.contains(object_id):
                        return None
                    time.sleep(0.05)
                return f"local copy of {object_id} never sealed"
            ok = False
            err = None
            try:
                if data_addr:
                    err = self._pull_data_plane(data_addr, object_id, size, shm)
                    ok = err is None
                    if not ok:
                        logger.warning(
                            "data-plane pull of %s from %s failed (%s); "
                            "falling back to RPC chunks", object_id,
                            data_addr, err)
                if not ok:
                    err = self._pull_rpc_chunks(peer, object_id, size, shm)
                    ok = err is None
            finally:
                shm.close()
                if not ok:
                    self.store.delete(object_id)  # discard partial segment
            if not ok:
                return err
            self.store.seal(object_id)
            return None
        finally:
            self._pull_budget.release(size)

    def _pull_data_plane(self, data_addr: str, object_id: ObjectID,
                         size: int, shm) -> Optional[str]:
        """Parallel-range pull: the object splits into N CONTIGUOUS ranges,
        one persistent raw socket streaming each straight into its slice of
        the destination segment — a single request/response round trip per
        stream, so the sender never idles between chunks (per-chunk RPCs
        would stall a full RTT every 16 MiB). The GIL releases during the
        kernel copies, so streams genuinely overlap; stream count adapts to
        the host's cores (extra streams on one core just thrash the GIL)."""
        cfg = get_config()
        n_streams = max(1, min(cfg.object_transfer_parallel_streams,
                               os.cpu_count() or 1,
                               size // (8 << 20) or 1))
        dest = memoryview(shm.buf)
        # 1 MiB-aligned contiguous ranges
        step = -(-size // n_streams)
        step = (step + ((1 << 20) - 1)) & ~((1 << 20) - 1)
        ranges = [(off, min(step, size - off))
                  for off in range(0, size, step)]

        def stripe(off: int, ln: int) -> None:
            client = None
            broken = False
            try:
                client = self._data_pool.acquire(data_addr)
                if not client.fetch_into(object_id, off, ln,
                                         dest[off:off + ln]):
                    raise ConnectionError(f"object gone at {data_addr}")
            except Exception:
                broken = True
                raise
            finally:
                if client is not None:
                    self._data_pool.release(client, broken=broken)

        from ray_tpu.core.data_plane import fan_out

        errors = fan_out([lambda r=r: stripe(*r) for r in ranges],
                         timeout=cfg.object_transfer_chunk_timeout_s * 2)
        return errors[0] if errors else None

    def _pull_rpc_chunks(self, peer: rpc.RpcClient, object_id: ObjectID,
                         size: int, shm) -> Optional[str]:
        """Fallback: pipelined chunk fetch over the control RPC channel."""
        cfg = get_config()
        chunk = cfg.object_transfer_chunk_size_bytes
        inflight: deque = deque()
        offset = 0
        while offset < size or inflight:
            while (offset < size
                   and len(inflight) < cfg.object_transfer_inflight_chunks):
                ln = min(chunk, size - offset)
                inflight.append((offset, ln, peer.call_future(
                    "fetch_object_chunk",
                    {"object_id": object_id, "offset": offset,
                     "length": ln})))
                offset += ln
            off, ln, fut = inflight.popleft()
            data = fut.result(timeout=cfg.object_transfer_chunk_timeout_s)
            if data is None or len(data) != ln:
                return (f"chunk at {off} of {object_id} unavailable "
                        f"at {peer.address}")
            shm.buf[off:off + ln] = data
        return None

    def rpc_push_object(self, conn, req_id, payload):
        """Owner-directed push (reference push_manager.h:29): stream a
        locally-held object into target raylets' stores so N readers don't
        all serialize on one source copy. Each completed delivery registers
        the new location with the owner, making it immediately pullable."""
        threading.Thread(
            target=self._push_to_targets,
            args=(payload["object_id"], list(payload.get("targets", ())),
                  payload.get("owner_address", "")),
            name="obj-push", daemon=True).start()
        return True

    def _push_to_targets(self, object_id: ObjectID, targets: List[str],
                         owner: str) -> None:
        # pinned for the whole fan-out: a spill mid-push would unlink the
        # segment under N concurrent streams
        with self.store.pinned_view(object_id) as buf:
            if buf is None:
                logger.warning("push of %s requested but object not local",
                               object_id)
                return
            src = memoryview(buf.view)

            def push_one(target: str) -> None:
                client = None
                broken = False
                try:
                    data_addr = self._peer(target).call(
                        "data_plane_addr", {}, timeout=10)
                    client = self._data_pool.acquire(data_addr)
                    try:
                        outcome = client.push_from(object_id, src)
                    except Exception:
                        broken = True
                        raise
                    finally:
                        self._data_pool.release(client, broken=broken)
                    # register ONLY delivered copies: a SKIP may mean a
                    # concurrent unsealed create that later fails — the
                    # target's own pull registers itself when it seals
                    if owner and outcome == "ok":
                        # one-shot notify; owner-side registration is
                        # idempotent and best-effort (pull still works
                        # through the primary copy if this is lost)
                        c = rpc.connect_with_retry(owner, timeout=5)
                        try:
                            c.notify("add_object_location",
                                     {"object_id": object_id,
                                      "raylet": target})
                        finally:
                            c.close()
                except Exception as e:
                    logger.warning("push of %s to %s failed: %s",
                                   object_id, target, e)

            from ray_tpu.core.data_plane import fan_out

            fan_out([lambda t=t: push_one(t) for t in targets],
                    timeout=get_config().object_transfer_chunk_timeout_s * 4)

    def _resolve_pulls(self, object_id: ObjectID, err: Optional[str] = None) -> None:
        with self._lock:
            waiters = self._pending_pulls.pop(object_id, [])
        if not waiters:
            return
        loc = self.store.lookup(object_id)
        for conn, req_id, pin in waiters:
            if pin:
                # pin BEFORE the reply so the object can't evict (or its
                # segment recycle) in the reply->attach window — cross-node
                # pulls land sealed-and-pinnable. A pin that misses means
                # the object vanished again: error, the reader re-pulls.
                pinned, reason = self.store.pin_ex(object_id)
                if pinned is None and reason == "pin_cap":
                    # at the max_pinned_fraction cap: transient copy-only
                    # grant, same contract as rpc_pull_object's cap path
                    pinned = self.store.pin(object_id, transient=True)
                    if pinned is not None:
                        pinned = (pinned[0], pinned[1], "copy_only")
                if pinned is not None:
                    self._track_pin(conn, object_id)
                    conn.reply(req_id, pinned)
                else:
                    conn.reply(req_id,
                               err or f"object {object_id} unavailable",
                               is_error=True)
            elif loc is not None:
                conn.reply(req_id, loc)
            else:
                conn.reply(req_id, err or f"object {object_id} unavailable", is_error=True)

    # ------------------------------------------------------------------ info
    def rpc_node_info(self, conn, req_id, payload):
        with self._lock:
            return {
                "node_id": self.node_id.binary(),
                "address": self._server.address,
                "resources_total": dict(self.resources_total),
                "resources_available": dict(self.resources_available),
                "labels": dict(self.labels),
                "num_workers": len(self._workers),
                "queued_tasks": len(self._queue),
            }
