"""Worker process entry point.

Equivalent of the reference's `python/ray/_private/workers/default_worker.py`
(entry `:165`): spawned by the raylet's worker pool, connects back, then
serves tasks until told to exit.

Two spawn modes share this module:

* cold: `python -m ray_tpu.core.worker_main --raylet ... --gcs ...` boots a
  fresh interpreter per worker (the classic path, and the fallback).
* warm: `--template` parks a fork-template ("zygote") process that preloads
  the heavy imports once and `os.fork()`s a ready worker per granted lease
  (see `worker_pool.py`); each forked child runs the same `run_worker`
  body a cold worker runs.
"""

from __future__ import annotations

import argparse
import logging
import time


def run_worker(raylet_address: str, gcs_address: str,
               log_level: str = "WARNING") -> None:
    """The worker body proper: connect, register, serve until the raylet
    link drops. Runs in cold-spawned processes AND in children forked from
    a template — keep it free of assumptions about interpreter freshness
    beyond what `worker_pool._forked_child_main` resets."""
    logging.basicConfig(
        level=log_level,
        format="%(asctime)s %(levelname)s worker %(name)s: %(message)s",
    )

    # `ray_tpu stack` support: SIGUSR1 dumps every thread's Python stack to
    # a per-pid file (reference `ray stack` uses py-spy; this is dep-free)
    import faulthandler
    import os
    import signal

    stack_dir = "/tmp/ray_tpu/stacks"
    os.makedirs(stack_dir, exist_ok=True)
    _stack_file = open(os.path.join(stack_dir, f"{os.getpid()}.txt"), "w")
    faulthandler.register(signal.SIGUSR1, file=_stack_file, all_threads=True)

    # Tee stdout/stderr to the raylet so drivers see task prints
    # (reference log_monitor tail-to-driver). Installed BEFORE the worker
    # connects — tasks can start executing the moment registration lands,
    # so lines buffer until the raylet client exists. logging handlers keep
    # their original stream objects, so runtime logs don't recurse.
    import sys as _sys

    import threading as _threading

    class _Tee:
        def __init__(self, stream, name):
            self._stream = stream
            self._name = name
            self._buf = ""
            self._pending = []
            self._lock = _threading.Lock()
            self.raylet = None  # set once connected

        def write(self, data):
            self._stream.write(data)
            with self._lock:
                self._buf += data
                if "\n" not in self._buf:
                    return
                *lines, self._buf = self._buf.split("\n")
                self._pending.extend(ln for ln in lines if ln.strip())
            self._drain()

        def _current_job(self):
            from ray_tpu.core.worker import current_worker

            w = current_worker()
            if w is None:
                return None
            jid = getattr(w._tls, "job_id", None)
            return jid.binary() if jid is not None else None

        def _drain(self):
            with self._lock:
                if self.raylet is None or not self._pending:
                    return
                lines, self._pending = self._pending, []
            try:
                self.raylet.notify("worker_log", {
                    "pid": os.getpid(), "stream": self._name, "lines": lines,
                    "job_id": self._current_job()})
            except Exception:
                pass

        def flush(self):
            self._stream.flush()

        def __getattr__(self, name):
            return getattr(self._stream, name)

    out_tee = _Tee(_sys.stdout, "stdout")
    err_tee = _Tee(_sys.stderr, "stderr")
    _sys.stdout = out_tee
    _sys.stderr = err_tee

    from ray_tpu.core.worker import CoreWorker, set_current_worker

    try:
        worker = CoreWorker(
            mode="worker", raylet_address=raylet_address,
            gcs_address=gcs_address, connect_timeout=10.0)
    except ConnectionError:
        return  # raylet is gone (e.g. shut down while we were starting)
    set_current_worker(worker)
    out_tee.raylet = err_tee.raylet = worker.raylet
    out_tee._drain()
    err_tee._drain()

    # Serve until the raylet connection drops (raylet died or killed us).
    try:
        while not worker.raylet.closed:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet", required=True)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--log-level", default="WARNING")
    parser.add_argument("--template", action="store_true",
                        help="run as a fork-template (zygote) process")
    parser.add_argument("--reply-fd", type=int, default=-1,
                        help="inherited fd for template protocol replies")
    args = parser.parse_args()

    if args.template:
        from ray_tpu.core.worker_pool import template_main

        template_main(args)
        return
    run_worker(args.raylet, args.gcs, log_level=args.log_level)


if __name__ == "__main__":
    main()
