"""Worker process entry point.

Equivalent of the reference's `python/ray/_private/workers/default_worker.py`
(entry `:165`): spawned by the raylet's worker pool, connects back, then
serves tasks until told to exit.
"""

from __future__ import annotations

import argparse
import logging
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet", required=True)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--log-level", default="WARNING")
    args = parser.parse_args()

    logging.basicConfig(
        level=args.log_level,
        format="%(asctime)s %(levelname)s worker %(name)s: %(message)s",
    )

    # `ray_tpu stack` support: SIGUSR1 dumps every thread's Python stack to
    # a per-pid file (reference `ray stack` uses py-spy; this is dep-free)
    import faulthandler
    import os
    import signal

    stack_dir = "/tmp/ray_tpu/stacks"
    os.makedirs(stack_dir, exist_ok=True)
    _stack_file = open(os.path.join(stack_dir, f"{os.getpid()}.txt"), "w")
    faulthandler.register(signal.SIGUSR1, file=_stack_file, all_threads=True)

    from ray_tpu.core.worker import CoreWorker, set_current_worker

    try:
        worker = CoreWorker(
            mode="worker", raylet_address=args.raylet, gcs_address=args.gcs,
            connect_timeout=10.0)
    except ConnectionError:
        return  # raylet is gone (e.g. shut down while we were starting)
    set_current_worker(worker)

    # Serve until the raylet connection drops (raylet died or killed us).
    try:
        while not worker.raylet.closed:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
