"""Exception types surfaced by the runtime.

Mirrors the reference's `python/ray/exceptions.py` surface (RayError,
RayTaskError, RayActorError, WorkerCrashedError, ObjectLostError,
GetTimeoutError) without its dependency on serialized C++ status codes.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Wraps the remote traceback so `get()` on the result re-raises with the
    remote stack attached (cf. reference RayTaskError.as_instanceof_cause).
    """

    def __init__(self, function_name: str, remote_traceback: str, cause: Exception | None = None):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{remote_traceback}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.remote_traceback, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:
            import cloudpickle

            cloudpickle.dumps(exc)
            cause = exc
        except Exception:
            cause = None  # unpicklable cause: carry only the traceback text
        return cls(function_name, tb, cause)


class ActorError(TaskError):
    """An actor method raised an exception."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed, killed, or out of restarts)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The raylet's memory monitor killed the worker under node memory
    pressure and the task's retry budget is exhausted (reference
    worker_killing_policy.h:34 + OutOfMemoryError in ray.exceptions)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled — by `ray_tpu.cancel()` (directly, or as part
    of a `recursive=True` tree walk) or by the job failure domain reaping a
    dead driver's work. A cancelled ref ALWAYS resolves to this error: the
    owner stamps it whether the task was still queued (raylet dequeue), was
    interrupted mid-execution (cooperative exception injection, or SIGKILL
    under force=True), or completed in the race window after cancel() was
    called (the late value is dropped so the outcome is deterministic).
    Never retried. Matched BY TYPE by callers, the workflow engine and the
    job storm; don't match the message."""


class ObjectLostError(RayTpuError):
    """An object was lost (e.g. node died) and could not be reconstructed."""


class OwnerDiedError(ObjectLostError):
    """The object's owner process is dead, so the value can never be
    produced or re-resolved: the owner holds the authoritative location
    and lineage for its objects (ownership model), and the job failure
    domain drops a dead job's primary copies during the reap. Surfaced by
    cross-job `get()` of a reaped job's object. A subclass of
    ObjectLostError so existing lost-object handling still applies;
    matched BY TYPE by the job storm — don't match the message."""


class ObjectStoreFullError(RayTpuError):
    """The local object store could not admit an object: eviction, spilling
    and pin release freed no headroom within `put_full_timeout_s` (or the
    store is spill-degraded — every configured spill dir is failing — and
    puts are flipped to backpressure). A typed, bounded outcome instead of
    silent overcommit past capacity (reference ObjectStoreFullError in
    ray.exceptions + plasma's PlasmaStoreFull). Matched BY TYPE by callers
    and the store storm; don't match the message."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` timed out."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class PendingCallsLimitExceeded(RayTpuError):
    """Back-pressure: too many in-flight calls to an actor."""


class RequestTimeoutError(RayTpuError, TimeoutError):
    """A serve request exceeded its end-to-end deadline (reference
    `RequestTimeoutError` semantics of serve's request_timeout_s). Raised
    at whichever point first observes expiry — the replica's pre-dequeue
    check, the batcher's batch-assembly check, or the router's deadline
    reaper — and mapped to HTTP 504 at the ingress. Matched BY TYPE by the
    storm harness and the edges; don't match the message."""


class BackPressureError(RayTpuError):
    """A serve request was shed by admission control: every replica of the
    target deployment is at its configured in-flight cap (or the ingress
    itself is at its cap). A fast, typed rejection — mapped to HTTP 503 —
    so sustained overload degrades to bounded-latency sheds instead of
    unbounded queue growth. Matched BY TYPE (edges, storm harness)."""


class PlacementInfeasibleError(RayTpuError):
    """A placement group's bundles cannot be satisfied by the current
    cluster. Raised at the reservation source and matched BY TYPE (elastic
    shrink in train/trainer.py keys on it); matching the message string
    would let a reword silently disable elastic recovery."""
