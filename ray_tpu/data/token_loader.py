"""ctypes binding for the native token-batch loader (src/loader/).

The LM-training input path: a C++ prefetch pool streams [batch, seq+1]
int32 windows out of a memory-mapped token file, so host IO overlaps device
compute (the role the reference's native object plane + datasource stack
plays for its training jobs). Falls back to a numpy implementation when the
toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Iterator, Optional

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "loader", "token_loader.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_BUILD_DIR, f"libloader-{digest}.so")
            if not os.path.exists(so_path):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = so_path + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC,
                     "-lpthread"],
                    check=True, capture_output=True)
                os.replace(tmp, so_path)
            lib = ctypes.CDLL(so_path)
            lib.loader_open.restype = ctypes.c_void_p
            lib.loader_open.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64, ctypes.c_int]
            lib.loader_next.restype = ctypes.c_int
            lib.loader_next.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int32)]
            lib.loader_num_tokens.restype = ctypes.c_uint64
            lib.loader_num_tokens.argtypes = [ctypes.c_void_p]
            lib.loader_batches_per_epoch.restype = ctypes.c_uint64
            lib.loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
            lib.loader_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            logger.warning("native loader unavailable; using numpy fallback",
                           exc_info=True)
            _lib_failed = True
        return _lib


class TokenLoader:
    """Streams [batch, seq_len+1] int32 batches from a flat token file.

    mode="random": uniform windows (infinite). mode="sequential": per-epoch
    shuffled disjoint windows. Split a batch row into inputs/targets with
    `batch[:, :-1]` / `batch[:, 1:]` (or feed as {"tokens": batch}).
    """

    def __init__(self, path: str, *, batch: int, seq_len: int,
                 n_threads: int = 2, seed: int = 0, mode: str = "random"):
        assert mode in ("random", "sequential"), mode
        self.path = path
        self.batch = batch
        self.seq_len = seq_len
        self.mode = mode
        self._handle = None
        self._fallback: Optional[np.ndarray] = None
        self._rng = np.random.default_rng(seed)
        self._seed = seed
        self._cursor = 0     # fallback sequential position
        self._order: Optional[np.ndarray] = None
        lib = _load_lib()
        if lib is not None:
            self._handle = lib.loader_open(
                path.encode(), batch, seq_len, n_threads, seed,
                1 if mode == "sequential" else 0)
            if not self._handle:
                raise FileNotFoundError(
                    f"{path}: unreadable or smaller than one window")
            import weakref

            self._finalizer = weakref.finalize(
                self, lib.loader_close, self._handle)
        else:
            self._fallback = np.fromfile(path, dtype=np.int32)
            if len(self._fallback) < seq_len + 1:
                raise FileNotFoundError(
                    f"{path}: unreadable or smaller than one window")
        self._out = np.empty((batch, seq_len + 1), np.int32)

    @property
    def num_tokens(self) -> int:
        if self._handle:
            return _lib.loader_num_tokens(self._handle)
        return len(self._fallback)

    @property
    def batches_per_epoch(self) -> int:
        if self._handle:
            return _lib.loader_batches_per_epoch(self._handle)
        return (len(self._fallback) // (self.seq_len + 1)) // self.batch

    def next(self) -> np.ndarray:
        """Next [batch, seq_len+1] batch (a copy owned by the caller)."""
        if self._handle:
            rc = _lib.loader_next(
                self._handle,
                self._out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if rc != 0:
                raise RuntimeError("loader stopped")
            return self._out.copy()
        w = self.seq_len + 1
        if self.mode == "sequential":
            n = len(self._fallback) // w
            starts = []
            for _ in range(self.batch):
                epoch, i = divmod(self._cursor, n)
                if self._order is None or i == 0:
                    self._order = np.random.default_rng(
                        self._seed + epoch).permutation(n)
                starts.append(self._order[i] * w)
                self._cursor += 1
        else:
            starts = self._rng.integers(0, len(self._fallback) - w + 1,
                                        self.batch)
        return np.stack([self._fallback[s:s + w] for s in starts])

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next()

    def close(self) -> None:
        if self._handle:
            self._finalizer.detach()
            _lib.loader_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
