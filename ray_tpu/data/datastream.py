"""Datastream: distributed datasets of object-store blocks.

Equivalent capability surface to the reference's Data library
(`python/ray/data/datastream.py:171`, blocks `python/ray/data/block.py:259`,
streaming executor `_internal/execution/streaming_executor.py:45`,
streaming split `_internal/iterator/stream_split_iterator.py:41`):

  - a dataset is a list of *blocks* living in the object store as ObjectRefs;
  - transforms are lazy: a logical op list, fused into one task per block at
    execution (the map-fusion optimization the reference's logical optimizer
    performs);
  - execution happens as parallel tasks over blocks; `iter_batches` streams
    block results without materializing the whole dataset on the driver;
  - `streaming_split(n)` hands per-worker iterators coordinated by a block-
    assignment actor (the reference's coordinator-actor design, SURVEY §H).

Blocks are columnar dicts of numpy arrays (the TPU-relevant layout: feeds
`jax.device_put` directly) or plain row lists for generic Python data.
"""

from __future__ import annotations

import builtins
import functools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef

Block = Union[List[Any], Dict[str, np.ndarray]]


def _block_len(block: Block) -> int:
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    return len(block)


def _block_rows(block: Block) -> List[Any]:
    if isinstance(block, dict):
        keys = list(block)
        return [{k: block[k][i] for k in keys}
                for i in builtins.range(_block_len(block))]
    return list(block)


def _rows_to_block(rows: List[Any]) -> Block:
    if rows and isinstance(rows[0], dict) and all(
            isinstance(v, (int, float, np.number, np.ndarray)) for v in rows[0].values()):
        keys = list(rows[0])
        try:
            return {k: np.asarray([r[k] for r in rows]) for k in keys}
        except Exception:
            return rows
    return rows


def _concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if _block_len(b) > 0]
    if not blocks:
        return []
    if all(isinstance(b, dict) for b in blocks):
        keys = list(blocks[0])
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    rows: List[Any] = []
    for b in blocks:
        rows.extend(_block_rows(b))
    return rows


def _slice_block(block: Block, start: int, end: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


# ------------------------------------------------------------------ ops


def _apply_ops(block: Block, ops: List[tuple]) -> Block:
    """Run the fused op chain on one block (executes inside a task)."""
    for op in ops:
        kind = op[0]
        if kind == "map_batches":
            fn = op[1]
            if isinstance(block, list):
                block = fn(_rows_to_block(block))
            else:
                block = fn(block)
        elif kind == "map":
            fn = op[1]
            block = _rows_to_block([fn(r) for r in _block_rows(block)])
        elif kind == "flat_map":
            fn = op[1]
            out: List[Any] = []
            for r in _block_rows(block):
                out.extend(fn(r))
            block = _rows_to_block(out)
        elif kind == "filter":
            fn = op[1]
            block = _rows_to_block([r for r in _block_rows(block) if fn(r)])
    return block


@ray_tpu.remote
def _exec_block(block_or_ref, ops: List[tuple]) -> Block:
    return _apply_ops(block_or_ref, ops)


class Datastream:
    """A lazy, distributed dataset. (alias: Dataset)"""

    def __init__(self, block_refs: List[ObjectRef], ops: Optional[List[tuple]] = None):
        self._block_refs = list(block_refs)
        self._ops: List[tuple] = list(ops or [])

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable[[Any], Any]) -> "Datastream":
        return Datastream(self._block_refs, self._ops + [("map", fn)])

    def map_batches(self, fn: Callable[[Block], Block], *,
                    batch_format: str = "numpy") -> "Datastream":
        return Datastream(self._block_refs, self._ops + [("map_batches", fn)])

    def flat_map(self, fn: Callable[[Any], Iterable[Any]]) -> "Datastream":
        return Datastream(self._block_refs, self._ops + [("flat_map", fn)])

    def filter(self, fn: Callable[[Any], bool]) -> "Datastream":
        return Datastream(self._block_refs, self._ops + [("filter", fn)])

    def repartition(self, num_blocks: int) -> "Datastream":
        ds = self.materialize()
        blocks = ray_tpu.get(ds._block_refs)
        whole = _concat_blocks(blocks)
        n = _block_len(whole)
        per = max(1, -(-n // num_blocks))
        new_refs = [ray_tpu.put(_slice_block(whole, i * per, min((i + 1) * per, n)))
                    for i in builtins.range(num_blocks) if i * per < n or i == 0]
        return Datastream(new_refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Datastream":
        ds = self.materialize()
        blocks = ray_tpu.get(ds._block_refs)
        rows: List[Any] = []
        for b in blocks:
            rows.extend(_block_rows(b))
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(rows))
        rows = [rows[i] for i in idx]
        nb = max(1, len(ds._block_refs))
        per = max(1, -(-len(rows) // nb))
        refs = [ray_tpu.put(_rows_to_block(rows[i:i + per]))
                for i in builtins.range(0, max(len(rows), 1), per)]
        return Datastream(refs)

    def union(self, other: "Datastream") -> "Datastream":
        a, b = self.materialize(), other.materialize()
        return Datastream(a._block_refs + b._block_refs)

    # ----------------------------------------------------------- execution
    def materialize(self) -> "Datastream":
        if not self._ops:
            return self
        refs = [_exec_block.remote(r, self._ops) for r in self._block_refs]
        return Datastream(refs)

    def _executed_refs(self) -> List[ObjectRef]:
        return self.materialize()._block_refs

    # ----------------------------------------------------------- consumers
    def count(self) -> int:
        return sum(_block_len(b) for b in ray_tpu.get(self._executed_refs()))

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ref in self._executed_refs():
            out.extend(_block_rows(ray_tpu.get(ref)))
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for ref in self._executed_refs():
            out.extend(_block_rows(ray_tpu.get(ref)))
        return out

    def schema(self) -> Optional[Dict[str, Any]]:
        for ref in self._executed_refs():
            b = ray_tpu.get(ref)
            if _block_len(b):
                if isinstance(b, dict):
                    return {k: v.dtype for k, v in b.items()}
                r = _block_rows(b)[0]
                return {k: type(v) for k, v in r.items()} if isinstance(r, dict) else {
                    "value": type(r)}
        return None

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def iter_rows(self) -> Iterator[Any]:
        for ref in self._executed_refs():
            yield from _block_rows(ray_tpu.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Block]:
        """Stream batches; blocks execute as tasks ahead of consumption."""
        refs = self._executed_refs()
        carry: Optional[Block] = None
        for ref in refs:
            block = ray_tpu.get(ref)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            i = 0
            while n - i >= batch_size:
                yield _slice_block(block, i, i + batch_size)
                i += batch_size
            if i < n:
                carry = _slice_block(block, i, n)
        if carry is not None and not drop_last:
            yield carry

    def split(self, n: int, *, equal: bool = False) -> List["Datastream"]:
        refs = self._executed_refs()
        if equal:
            blocks = ray_tpu.get(refs)
            whole = _concat_blocks(blocks)
            total = _block_len(whole)
            per = total // n
            return [Datastream([ray_tpu.put(_slice_block(whole, i * per, (i + 1) * per))])
                    for i in builtins.range(n)]
        out: List[List[ObjectRef]] = [[] for _ in builtins.range(n)]
        for i, r in enumerate(refs):
            out[i % n].append(r)
        return [Datastream(r) for r in out]

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Per-consumer iterators fed by a coordinator actor (SURVEY §H)."""
        refs = self._executed_refs()
        coord = _SplitCoordinator.options(num_cpus=0).remote(
            [r for r in refs], n)
        return [DataIterator(coord, i) for i in builtins.range(n)]

    def __repr__(self):
        return (f"Datastream(num_blocks={len(self._block_refs)}, "
                f"pending_ops={len(self._ops)})")


Dataset = Datastream  # the reference renamed Dataset->Datastream in this era


@ray_tpu.remote
class _SplitCoordinator:
    """Serves block refs round-robin to n consumers, epoch-synchronized."""

    def __init__(self, refs: List[ObjectRef], n: int):
        self.refs = refs
        self.n = n
        self.epoch_positions: Dict[int, int] = {}

    def next_block(self, consumer: int):
        pos = self.epoch_positions.get(consumer, consumer)
        if pos >= len(self.refs):
            return None
        self.epoch_positions[consumer] = pos + self.n
        return self.refs[pos]

    def reset(self, consumer: int):
        self.epoch_positions[consumer] = consumer
        return True


class DataIterator:
    """Per-worker view of a streaming split (cf. reference DataIterator)."""

    def __init__(self, coordinator, index: int):
        self._coord = coordinator
        self._index = index

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        ray_tpu.get(self._coord.reset.remote(self._index))
        carry: Optional[Block] = None
        while True:
            ref = ray_tpu.get(self._coord.next_block.remote(self._index))
            if ref is None:
                break
            block = ray_tpu.get(ref)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            i = 0
            while n - i >= batch_size:
                yield _slice_block(block, i, i + batch_size)
                i += batch_size
            if i < n:
                carry = _slice_block(block, i, n)
        if carry is not None and not drop_last:
            yield carry

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=256):
            yield from _block_rows(batch)

    def __reduce__(self):
        return (DataIterator, (self._coord, self._index))


# ------------------------------------------------------------ constructors


def from_items(items: List[Any], *, parallelism: int = 8) -> Datastream:
    n = max(1, min(parallelism, len(items) or 1))
    per = -(-len(items) // n) if items else 1
    refs = [ray_tpu.put(_rows_to_block(items[i:i + per]))
            for i in builtins.range(0, max(len(items), 1), per)]
    return Datastream(refs)


def range(n: int, *, parallelism: int = 8) -> Datastream:  # noqa: A001
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        refs.append(ray_tpu.put({"id": np.arange(start, end)}))
    return Datastream(refs)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Datastream:
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        ids = np.arange(start, end)
        data = np.broadcast_to(ids.reshape(-1, *([1] * len(shape))),
                               (end - start, *shape)).copy()
        refs.append(ray_tpu.put({"data": data}))
    return Datastream(refs)


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]],
               *, parallelism: int = 8) -> Datastream:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    n = len(next(iter(arrays.values())))
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        refs.append(ray_tpu.put({k: v[start:end] for k, v in arrays.items()}))
    return Datastream(refs)


def read_text(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        with open(path) as f:
            return [{"text": line.rstrip("\n")} for line in f]

    return Datastream([load.remote(p) for p in paths])


def read_json(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        import json

        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return _rows_to_block(rows)

    return Datastream([load.remote(p) for p in paths])


def read_csv(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        import csv

        with open(path) as f:
            return _rows_to_block([dict(r) for r in csv.DictReader(f)])

    return Datastream([load.remote(p) for p in paths])


def read_parquet(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        import pyarrow.parquet as pq

        table = pq.read_table(path)
        return {c: table[c].to_numpy() for c in table.column_names}

    return Datastream([load.remote(p) for p in paths])
