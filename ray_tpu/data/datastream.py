"""Datastream: distributed datasets of object-store blocks.

Equivalent capability surface to the reference's Data library
(`python/ray/data/datastream.py:171`, blocks `python/ray/data/block.py:259`,
streaming executor `_internal/execution/streaming_executor.py:45`,
streaming split `_internal/iterator/stream_split_iterator.py:41`):

  - a dataset is a list of *blocks* living in the object store as ObjectRefs;
  - transforms are lazy: a logical op list, fused into one task per block at
    execution (the map-fusion optimization the reference's logical optimizer
    performs);
  - execution happens as parallel tasks over blocks; `iter_batches` streams
    block results without materializing the whole dataset on the driver;
  - `streaming_split(n)` hands per-worker iterators coordinated by a block-
    assignment actor (the reference's coordinator-actor design, SURVEY §H).

Blocks are columnar dicts of numpy arrays (the TPU-relevant layout: feeds
`jax.device_put` directly) or plain row lists for generic Python data.
"""

from __future__ import annotations

import builtins
import functools
from collections import deque
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef

Block = Union[List[Any], Dict[str, np.ndarray]]


def _block_len(block: Block) -> int:
    if isinstance(block, dict):
        return len(next(iter(block.values()))) if block else 0
    return len(block)


def _block_rows(block: Block) -> List[Any]:
    if isinstance(block, dict):
        keys = list(block)
        return [{k: block[k][i] for k in keys}
                for i in builtins.range(_block_len(block))]
    return list(block)


def _rows_to_block(rows: List[Any]) -> Block:
    if rows and isinstance(rows[0], dict) and all(
            isinstance(v, (int, float, str, np.number, np.str_, np.ndarray))
            for v in rows[0].values()):
        keys = list(rows[0])
        try:
            return {k: np.asarray([r[k] for r in rows]) for k in keys}
        except Exception:
            return rows
    return rows


def _concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if _block_len(b) > 0]
    if not blocks:
        return []
    if all(isinstance(b, dict) for b in blocks):
        keys = list(blocks[0])
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    rows: List[Any] = []
    for b in blocks:
        rows.extend(_block_rows(b))
    return rows


def _slice_block(block: Block, start: int, end: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


# ------------------------------------------------------------------ ops


def _apply_ops_timed(block: Block, ops: List[tuple]):
    """_apply_ops + per-op wall time, for Datastream.stats()
    (reference Dataset.stats() per-operator execution summary)."""
    import time

    timings = []
    for idx, op in enumerate(ops):
        t0 = time.perf_counter()
        block = _apply_ops(block, [op])
        # Keyed by (position, kind): a chain with two map ops gets distinct
        # per-operator lines instead of one merged bucket.
        timings.append((idx, op[0], time.perf_counter() - t0))
    return block, timings


def _apply_ops(block: Block, ops: List[tuple]) -> Block:
    """Run the fused op chain on one block (executes inside a task)."""
    for op in ops:
        kind = op[0]
        if kind == "map_batches":
            fn = op[1]
            if isinstance(block, list):
                block = fn(_rows_to_block(block))
            else:
                block = fn(block)
        elif kind == "map":
            fn = op[1]
            block = _rows_to_block([fn(r) for r in _block_rows(block)])
        elif kind == "flat_map":
            fn = op[1]
            out: List[Any] = []
            for r in _block_rows(block):
                out.extend(fn(r))
            block = _rows_to_block(out)
        elif kind == "filter":
            fn = op[1]
            block = _rows_to_block([r for r in _block_rows(block) if fn(r)])
        elif kind == "filter_expr":
            pred = op[1]
            if isinstance(block, dict):
                mask = pred.mask(block)
                block = {k: np.asarray(v)[mask] for k, v in block.items()}
            else:
                block = _rows_to_block(
                    [r for r in _block_rows(block) if pred(r)])
        elif kind == "limit":
            block = _slice_block(block, 0, op[1])
    return block


@ray_tpu.remote
def _exec_block(block_or_ref, ops: List[tuple]) -> Block:
    return _apply_ops(block_or_ref, ops)


@ray_tpu.remote
def _count_rows_after_ops(block_or_ref, ops: List[tuple]) -> int:
    """Row count of a block after the op chain (ops=[] = raw block length —
    the zip()/count() shared counting helper; only ints ship to the driver).
    """
    return _block_len(_apply_ops(block_or_ref, ops))


def _apply_batched(fn, batch_size: int, block: Block) -> Block:
    """Slice a block into <=batch_size row batches, apply fn, re-concat."""
    if isinstance(block, list):
        block = _rows_to_block(block)
    n = _block_len(block)
    if n <= batch_size:
        return fn(block)
    outs = [fn(_slice_block(block, i, min(i + batch_size, n)))
            for i in builtins.range(0, n, batch_size)]
    return _concat_blocks(outs)


class _SourceSpec:
    """Lazy, pushdown-capable read (reference `python/ray/data/datasource/
    parquet_datasource.py:179,214`): the reader tasks are NOT submitted at
    read_*() time — they launch when blocks are first needed, with the
    plan's leading select/predicate ops folded into the reader call, so
    column pruning and row-group filtering happen at the FILE layer.

    Pushed ops stay in the op chain (selects and predicate filters are
    idempotent), so no plan surgery is needed for correctness."""

    def __init__(self, kind: str, paths: List[str], loader,
                 supports_columns: bool = False,
                 supports_filters: bool = False,
                 columns: Optional[List[str]] = None,
                 filters: Optional[list] = None):
        self.kind = kind
        self.paths = list(paths)
        self.loader = loader
        self.supports_columns = supports_columns
        self.supports_filters = supports_filters
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None
        # branched pipelines (two streams derived from one read) share one
        # scan per distinct pushdown instead of re-reading every file
        self._submitted: Dict[Any, List[ObjectRef]] = {}

    def pushdown(self, ops: List[tuple]):
        """(columns, filters, pushed_labels) for the optimized chain: the
        leading run of select-only projections and predicate filters folds
        into the reader; the scan stops at the first op that could change
        names or rows in a way the reader can't express."""
        from ray_tpu.data.plan import optimize

        optimized, _ = optimize(list(ops))
        columns = self.columns
        filters = list(self.filters or [])
        # columns of filters pushed FROM THE CHAIN: the chain re-applies
        # them (idempotently), so the read must keep those columns even
        # when a later select drops them
        chain_filter_cols: List[str] = []
        pushed: List[str] = []
        for op in optimized:
            if op[0] == "project" and self.supports_columns:
                spec = op[1]
                steps = spec.get("steps") or [spec]
                first = steps[0]
                if "select" not in first:
                    break  # drop/rename head: column set not derivable
                if columns is None:
                    sel = list(first["select"])
                    columns = sel + [c for c in chain_filter_cols
                                     if c not in sel]
                    pushed.append(f"columns={sel}")
                if not all("select" in s for s in steps):
                    break  # renames ahead: later predicate names unsafe
            elif op[0] == "filter_expr" and self.supports_filters:
                pred = op[1]
                if columns is not None and pred.column not in columns:
                    # predicate on a column the pushed select dropped: the
                    # executor path must raise (as it always did), not the
                    # reader silently filter on an unread column
                    break
                filters.append(pred.as_tuple())
                chain_filter_cols.append(pred.column)
                pushed.append(f"filter[{pred!r}]")
            else:
                break
        return columns, (filters or None), pushed

    def submit(self, ops: List[tuple]) -> List[ObjectRef]:
        columns, filters, _ = self.pushdown(ops)
        key = (tuple(columns) if columns else None,
               tuple(filters) if filters else None)
        if key not in self._submitted:
            self._submitted[key] = [self.loader.remote(p, columns, filters)
                                    for p in self.paths]
        return self._submitted[key]

    def describe(self, ops: List[tuple]) -> str:
        columns, filters, pushed = self.pushdown(ops)
        extra = f", pushdown: {' '.join(pushed)}" if pushed else ""
        return (f"Source[{self.kind}, {len(self.paths)} files{extra}]")


class Datastream:
    """A lazy, distributed dataset. (alias: Dataset)"""

    def __init__(self, block_refs: Optional[List[ObjectRef]],
                 ops: Optional[List[tuple]] = None,
                 source: Optional[_SourceSpec] = None):
        self._refs: Optional[List[ObjectRef]] = (
            list(block_refs) if block_refs is not None else None)
        self._source = source
        if self._refs is None and source is None:
            raise ValueError("Datastream needs block refs or a source")
        # LOGICAL operator chain (data/plan.py); execution sites lower it
        # through the optimizer passes via _physical_ops
        self._ops: List[tuple] = list(ops or [])

    @property
    def _block_refs(self) -> List[ObjectRef]:
        """Materialize the source on first use (reader tasks launch with
        this stream's pushed-down columns/filters)."""
        if self._refs is None:
            self._refs = self._source.submit(self._ops)
        return self._refs

    @_block_refs.setter
    def _block_refs(self, refs: List[ObjectRef]) -> None:
        self._refs = list(refs)

    def _derive(self, extra_ops: List[tuple]) -> "Datastream":
        """Lazy transform: keep the unsubmitted source flowing so later
        ops can still push into the readers."""
        if self._refs is None:
            return Datastream(None, self._ops + extra_ops,
                              source=self._source)
        return Datastream(self._refs, self._ops + extra_ops)


    @property
    def _physical_ops(self) -> List[tuple]:
        """Optimizer passes + lowering over the logical chain (reference
        _internal/logical optimizer -> physical plan)."""
        from ray_tpu.data.plan import lower, optimize

        ops, _ = optimize(self._ops)
        return lower(ops)

    def explain(self) -> str:
        """Printable logical plan, applied rules, optimized plan, and
        physical op list (reference Dataset.explain). For lazy sources the
        header shows the reader-level pushdown (columns/filters) without
        submitting any read."""
        from ray_tpu.data.plan import explain_ops

        source_desc = (self._source.describe(self._ops)
                       if self._refs is None else None)
        text = explain_ops(self.num_blocks(), self._ops,
                           source_desc=source_desc)
        print(text)
        return text

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable[[Any], Any]) -> "Datastream":
        return self._derive([("map", fn)])

    def map_batches(self, fn, *,
                    batch_format: str = "numpy",
                    batch_size: Optional[int] = None,
                    compute: Optional["ActorPoolStrategy"] = None,
                    fn_constructor_args: tuple = ()) -> "Datastream":
        """Per-batch transform. Without `batch_size` each block is one
        batch; with it, blocks are re-sliced so `fn` sees at most
        `batch_size` rows per call. `fn` may be a callable (task compute,
        lazy) or a class (stateful UDF) with `compute=ActorPoolStrategy(...)`
        — then a pool of actors is created, each constructing the class once
        and streaming batches through `__call__` (reference
        actor_pool_map_operator.py)."""
        if compute is not None or isinstance(fn, type):
            if not isinstance(fn, type):
                raise ValueError(
                    "compute=ActorPoolStrategy requires a class UDF")
            compute = compute or ActorPoolStrategy()
            return self._map_batches_actors(
                fn, compute, fn_constructor_args, batch_size)
        if batch_size is not None:
            fn = functools.partial(_apply_batched, fn, batch_size)
        return self._derive([("map_batches", fn)])

    def _map_batches_actors(self, fn_cls: type,
                            compute: "ActorPoolStrategy",
                            ctor_args: tuple,
                            batch_size: Optional[int] = None) -> "Datastream":
        """Eagerly runs this stage (with all pending lazy ops) through a
        pool of stateful actors; returns a new lazy Datastream over the
        result blocks."""
        # min_size is the pre-warm floor (expensive ctors), max_size the cap
        n_actors = max(1, min(compute.max_size,
                              max(compute.min_size, len(self._block_refs))))

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, ops, args):
                self._ops = ops
                self._udf = fn_cls(*args)

            def apply(self, block) -> Block:
                block = _apply_ops(block, self._ops)
                if isinstance(block, list):
                    block = _rows_to_block(block)
                if batch_size is not None:
                    return _apply_batched(self._udf, batch_size, block)
                return self._udf(block)

        actors = [_MapWorker.options(**compute.actor_options).remote(
            self._physical_ops, ctor_args) for _ in builtins.range(n_actors)]
        refs = [actors[i % n_actors].apply.remote(r)
                for i, r in enumerate(self._block_refs)]
        # block until all results are in the store (the driver owns them and
        # they outlive the pool), but never pull them through the driver
        ray_tpu.wait(refs, num_returns=len(refs))
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        return Datastream(refs)

    def flat_map(self, fn: Callable[[Any], Iterable[Any]]) -> "Datastream":
        return self._derive([("flat_map", fn)])

    def filter(self, fn) -> "Datastream":
        """Row filter. A `col("x") > 5` predicate expression runs as a
        vectorized mask AND pushes into parquet readers (row-group pruning
        by statistics); a plain callable filters row-wise in the executor
        (opaque to pushdown, like the reference's non-expression UDFs)."""
        from ray_tpu.data.expressions import ColumnPredicate

        if isinstance(fn, ColumnPredicate):
            return self._derive([("filter_expr", fn)])
        return self._derive([("filter", fn)])

    # stats-aware partitioning: target rows per output block when the
    # caller doesn't pick a count (reference streaming executor's
    # resource-budgeted partitioning)
    TARGET_ROWS_PER_BLOCK = 8192

    def _auto_num_blocks(self) -> int:
        """Estimate output partitions from a one-block row-count sample:
        total_rows ~= rows(first block) * num_blocks, sized to
        TARGET_ROWS_PER_BLOCK."""
        if not self._block_refs:
            return 1
        sample = ray_tpu.get(_count_rows_after_ops.remote(
            self._block_refs[0], self._physical_ops))
        est_total = sample * len(self._block_refs)
        return builtins.max(
            1, builtins.min(4 * len(self._block_refs),
                            -(-est_total // self.TARGET_ROWS_PER_BLOCK)))

    def repartition(self, num_blocks: Optional[int] = None) -> "Datastream":
        """Task-based all-to-all repartition (round-robin rows);
        num_blocks=None sizes partitions from sampled row stats."""
        from ray_tpu.data.shuffle import shuffle_refs

        return Datastream(shuffle_refs(
            self._block_refs, self._physical_ops, mode="random",
            num_partitions=num_blocks or self._auto_num_blocks(), seed=0))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Datastream":
        """Distributed two-stage shuffle; the driver never sees the rows
        (cf. reference `_internal/push_based_shuffle.py`). num_blocks=None
        keeps the input partitioning (or pass a count; see repartition for
        the stats-aware sizing)."""
        from ray_tpu.data.shuffle import shuffle_refs

        return Datastream(shuffle_refs(
            self._block_refs, self._physical_ops, mode="random", seed=seed,
            num_partitions=num_blocks))

    def sort(self, key: Union[str, Callable[[Any], Any]],
             descending: bool = False) -> "Datastream":
        """Distributed range-partition sort (sample boundaries → partition
        map tasks → per-range merge tasks; cf. reference sort exchange)."""
        from ray_tpu.data.shuffle import shuffle_refs

        out = Datastream(shuffle_refs(
            self._block_refs, self._physical_ops, mode="sort", key=key))
        if descending:
            refs = out._block_refs[::-1]
            rev = ray_tpu.remote(_reverse_block)
            return Datastream([rev.remote(r) for r in refs])
        return out

    def groupby(self, key: Union[str, Callable[[Any], Any]]) -> "GroupedData":
        """Hash-partition rows so each key's rows co-locate, then aggregate
        per partition (cf. reference `grouped_data.py`)."""
        from ray_tpu.data.shuffle import shuffle_refs

        refs = shuffle_refs(self._block_refs, self._physical_ops, mode="hash", key=key)
        return GroupedData(refs, key)

    def union(self, other: "Datastream") -> "Datastream":
        a, b = self.materialize(), other.materialize()
        return Datastream(a._block_refs + b._block_refs)

    def zip(self, other: "Datastream") -> "Datastream":
        """Column-wise zip. Runs as one task per left block that pulls only
        the overlapping right-side blocks — rows never land on the driver."""
        a_refs = self._executed_refs()
        b_refs = other._executed_refs()
        a_sizes = ray_tpu.get([_count_rows_after_ops.remote(r, []) for r in a_refs])
        b_sizes = ray_tpu.get([_count_rows_after_ops.remote(r, []) for r in b_refs])
        if sum(a_sizes) != sum(b_sizes):
            raise ValueError(
                f"zip requires equal lengths: {sum(a_sizes)} vs {sum(b_sizes)}")
        b_starts = np.cumsum([0] + b_sizes[:-1]).tolist()
        merge = ray_tpu.remote(_zip_merge)
        out_refs, start = [], 0
        for aref, asz in zip(a_refs, a_sizes):
            end = start + asz
            picks, ranges = [], []
            for bref, bsz, bstart in zip(b_refs, b_sizes, b_starts):
                bend = bstart + bsz
                if bend <= start or bstart >= end:
                    continue
                picks.append(bref)
                ranges.append((max(start, bstart) - bstart,
                               min(end, bend) - bstart))
            out_refs.append(merge.remote(aref, ranges, *picks))
            start = end
        return Datastream(out_refs)

    def limit(self, n: int) -> "Datastream":
        """First n rows. Executes blocks incrementally and stops as soon as
        n rows are covered — pending ops never run on the untouched tail,
        and the LimitPushdown pass hops the limit over row-preserving ops
        so their UDFs touch at most n rows of each block."""
        from ray_tpu.data.plan import lower, optimize

        optimized, _ = optimize(self._ops + [("limit", n)])
        out_refs, seen = [], 0
        for ref in self._block_refs:
            if seen >= n:
                break
            # per-block remaining budget: rewrite every limit op's n
            ops = lower([("limit", n - seen) if op[0] == "limit" else op
                         for op in optimized])
            out = _exec_block.remote(ref, ops)
            out_refs.append(out)
            seen += _block_len(ray_tpu.get(out))
        return Datastream(out_refs)

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]) -> "Datastream":
        def add(block: Block) -> Block:
            if not isinstance(block, dict):
                rows = _block_rows(block)
                block = _rows_to_block(rows)
                if not isinstance(block, dict):
                    raise TypeError("add_column requires columnar blocks")
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Datastream":
        return self._derive([("project", {"drop": list(cols)})])

    def select_columns(self, cols: List[str]) -> "Datastream":
        return self._derive([("project", {"select": list(cols)})])

    def rename_columns(self, mapping: Dict[str, str]) -> "Datastream":
        return self._derive([("project", {"rename": dict(mapping)})])

    # ----------------------------------------------------------- execution
    def materialize(self) -> "Datastream":
        if not self._ops:
            return self
        refs = [_exec_block.remote(r, self._physical_ops) for r in self._block_refs]
        return Datastream(refs)

    def _executed_refs(self) -> List[ObjectRef]:
        return self.materialize()._block_refs

    def _stream_refs(self, max_inflight: Optional[int] = None,
                     memory_budget: Optional[int] = None) -> Iterator[ObjectRef]:
        """Backpressured streaming execution (reference
        `_internal/execution/streaming_executor.py:45`): yield executed block
        refs in order while keeping at most `max_inflight` block tasks
        submitted-but-unconsumed AND at most `memory_budget` bytes of
        PRODUCED-but-unconsumed results (the per-operator memory quota of
        the reference's streaming executor): an operator whose outputs
        balloon stops getting new submissions until the consumer drains,
        regardless of the count window."""
        if not self._ops:
            yield from self._block_refs
            return
        from ray_tpu.core.config import get_config

        cfg = get_config()
        if max_inflight is None:
            max_inflight = cfg.data_max_inflight_blocks
        if memory_budget is None:
            memory_budget = cfg.data_op_memory_budget_bytes
        from ray_tpu.core.api import _global_worker

        w = _global_worker()

        def produced_bytes(refs) -> int:
            total = 0
            for r in refs:
                sz = w.object_size(r)  # None while the task still runs
                if sz:
                    total += sz
            return total

        inflight: deque = deque()
        ops = self._physical_ops
        for r in self._block_refs:
            while len(inflight) >= max_inflight or (
                    inflight and produced_bytes(inflight) >= memory_budget):
                yield inflight.popleft()
            inflight.append(_exec_block.remote(r, ops))
        while inflight:
            yield inflight.popleft()

    # ----------------------------------------------------------- consumers
    def count(self) -> int:
        # CountProjection pass (reference _internal/logical optimizer):
        # trailing row-preserving ops (map / project) are dropped — a
        # map-only chain counts SOURCE blocks without running any UDF —
        # and counting ships per-block row COUNTS, never block data.
        from ray_tpu.data.plan import lower, ops_for_count, optimize

        ops, _ = ops_for_count(optimize(self._ops)[0])
        ops = lower(ops)
        return sum(ray_tpu.get(
            [_count_rows_after_ops.remote(r, ops) for r in self._block_refs]))

    def _column_reduce(self, col: str, block_fn, combine):
        task = ray_tpu.remote(
            lambda b, ops: block_fn(_apply_ops(b, ops), col))
        parts = [p for p in ray_tpu.get(
            [task.remote(r, self._physical_ops) for r in self._block_refs])
            if p is not None]
        if not parts:
            raise ValueError(f"no rows with column {col!r}")
        return combine(parts)

    def sum(self, col: str):
        return self._column_reduce(col, _block_col_sum, lambda ps: sum(ps))

    def min(self, col: str):
        return self._column_reduce(col, _block_col_min, lambda ps: builtins.min(ps))

    def max(self, col: str):
        return self._column_reduce(col, _block_col_max, lambda ps: builtins.max(ps))

    def mean(self, col: str):
        pairs = self._column_reduce(
            col, _block_col_sum_count, lambda ps: ps)
        total = sum(p[0] for p in pairs)
        cnt = sum(p[1] for p in pairs)
        return total / builtins.max(cnt, 1)

    def std(self, col: str, ddof: int = 1):
        vals = np.concatenate([np.atleast_1d(v) for v in self._column_values(col)])
        return float(np.std(vals, ddof=ddof))

    def unique(self, col: str) -> List[Any]:
        vals = np.concatenate([np.atleast_1d(v) for v in self._column_values(col)])
        return sorted(np.unique(vals).tolist())

    def _column_values(self, col: str) -> List[np.ndarray]:
        task = ray_tpu.remote(lambda b, ops: _block_col(_apply_ops(b, ops), col))
        return [v for v in ray_tpu.get(
            [task.remote(r, self._physical_ops) for r in self._block_refs]) if v is not None]

    # ------------------------------------------------------------- writers
    def _write(self, path_prefix: str, ext: str, write_block) -> List[str]:
        import os

        os.makedirs(path_prefix, exist_ok=True)
        task = ray_tpu.remote(
            lambda b, ops, p: write_block(_apply_ops(b, ops), p))
        paths = [os.path.join(path_prefix, f"part-{i:05d}.{ext}")
                 for i in builtins.range(len(self._block_refs))]
        ray_tpu.get([task.remote(r, self._physical_ops, p)
                     for r, p in zip(self._block_refs, paths)])
        return paths

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json", _write_block_json)

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv", _write_block_csv)

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet", _write_block_parquet)

    def write_tfrecords(self, path: str) -> List[str]:
        return self._write(path, "tfrecords", _write_block_tfrecords)

    def train_test_split(self, test_size: Union[int, float], *,
                         shuffle: bool = False, seed: Optional[int] = None):
        """(train, test) split (reference Datastream.train_test_split)."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        n = ds.count()
        n_test = int(n * test_size) if isinstance(test_size, float) else test_size
        return ds.split_at_indices([n - n_test])

    def to_pandas(self):
        """Materialize into one DataFrame (reference Datastream.to_pandas)."""
        import pandas as pd

        rows = self.take_all()
        if not rows:
            return pd.DataFrame()
        if isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})

    def to_arrow(self):
        """Materialize into one pyarrow Table."""
        import pyarrow as pa

        return pa.Table.from_pandas(self.to_pandas(), preserve_index=False)

    def split_at_indices(self, indices: List[int]) -> List["Datastream"]:
        """Split into len(indices)+1 streams at global row offsets. Each
        piece keeps the source's block parallelism so downstream
        streaming_split/map fan-out isn't collapsed to one block."""
        rows = self.take_all()
        out = []
        prev = 0
        par = max(1, self.num_blocks())
        for idx in list(indices) + [len(rows)]:
            out.append(from_items(rows[prev:idx], parallelism=par))
            prev = idx
        return out

    def split_proportionately(self, proportions: List[float]
                              ) -> List["Datastream"]:
        """Split by fractions; a final stream carries the remainder
        (reference Dataset.split_proportionately). [0.7, 0.2] -> three
        streams of ~70%/20%/10%."""
        if not proportions or any(p <= 0 for p in proportions):
            raise ValueError("proportions must be positive")
        if sum(proportions) >= 1.0:
            raise ValueError("proportions must sum to < 1 "
                             "(the remainder forms the last split)")
        n = self.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            # round, not truncate: float accumulation (0.7+0.2=0.8999…)
            # must not shave a row off a split boundary
            indices.append(round(n * acc))
        return self.split_at_indices(indices)

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Datastream":
        """Bernoulli row sample at `fraction` (reference
        Dataset.random_sample): each block filters locally with a
        per-block rng — no shuffle, no driver materialization."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        import random as _random

        base = seed if seed is not None else _random.randrange(2**31)

        def sample(block, _base=base, _frac=fraction):
            rows = _block_rows(block)
            # per-block rng derived from (seed, block content checksum):
            # distinct blocks sample independently, and a retried/lineage-
            # re-executed block reproduces its original sample
            csum = len(rows)
            if isinstance(block, dict) and block:
                first = np.ascontiguousarray(next(iter(block.values())))
                if first.size and first.dtype != object:
                    csum = int(first.view(np.uint8).sum())
                elif first.size:  # object columns: hash a stable prefix
                    csum = hash(repr(first.ravel()[0])) & 0x7FFFFFFF
            rng = np.random.default_rng((_base, csum))
            keep = rng.random(len(rows)) < _frac
            return _rows_to_block([r for r, k in zip(rows, keep) if k])

        return self.map_batches(sample)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Datastream":
        """Shuffle the BLOCK order only — O(1) metadata, no data moves
        (reference Dataset.randomize_block_order; the cheap shuffle used
        between epochs when a full row shuffle is too expensive)."""
        import copy
        import random as _random

        if self._refs is None:
            # lazy source: block order IS file order — shuffle the paths and
            # stay lazy (pushdown, input_files, footer schema all survive)
            source = copy.copy(self._source)
            source.paths = list(source.paths)
            _random.Random(seed).shuffle(source.paths)
            source._submitted = {}
            return Datastream(None, self._ops, source=source)
        refs = list(self._refs)
        _random.Random(seed).shuffle(refs)
        return Datastream(refs, self._ops)

    def take_batch(self, batch_size: int = 20) -> Block:
        """First up-to-batch_size rows as one columnar batch (reference
        Dataset.take_batch)."""
        return _rows_to_block(self.take(batch_size))

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def size_bytes(self) -> int:
        """Total materialized block bytes (reference Dataset.size_bytes)."""
        total = 0
        for ref in self._stream_refs():
            b = ray_tpu.get(ref)
            if isinstance(b, dict):
                total += sum(np.asarray(v).nbytes for v in b.values())
            else:
                import sys as _sys

                total += sum(_sys.getsizeof(r) for r in b)
        return total

    def input_files(self) -> List[str]:
        """Source files feeding this stream, [] for in-memory sources
        (reference Dataset.input_files)."""
        return list(self._source.paths) if self._source is not None else []

    def to_numpy_refs(self) -> List["ObjectRef"]:
        """Object refs of the executed blocks (dict-of-numpy form),
        without pulling them to the driver (reference
        Dataset.to_numpy_refs)."""
        return list(self._stream_refs())

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ref in self._stream_refs():
            out.extend(_block_rows(ray_tpu.get(ref)))
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for ref in self._stream_refs():
            out.extend(_block_rows(ray_tpu.get(ref)))
        return out

    def schema(self) -> Optional[Dict[str, Any]]:
        """Column name -> dtype. For an unsubmitted parquet source whose op
        chain can't invent columns, this reads only the file FOOTER
        (reference ParquetDatasource metadata-only schema) — no data task
        runs. Otherwise the first non-empty block is peeked."""
        if (self._refs is None and self._source.kind == "parquet"
                and all(op[0] in ("project", "filter_expr", "limit", "filter")
                        for op in self._ops)):
            import pyarrow as pa
            import pyarrow.parquet as pq

            arrow_schema = pq.read_schema(self._source.paths[0])
            # reader-level column pruning (source columns= plus pushed-down
            # selects/filter columns) applies to TOP-LEVEL file columns
            read_cols, _, _ = self._source.pushdown(self._ops)
            top = [n for n in arrow_schema.names
                   if read_cols is None or n in read_cols]

            def leaves(name, typ):
                """Mirror _table_to_block: structs flatten to dotted keys;
                leaf dtypes match what the numpy block will hold."""
                if pa.types.is_struct(typ):
                    for field in typ:
                        yield from leaves(f"{name}.{field.name}", field.type)
                    return
                if pa.types.is_dictionary(typ):
                    typ = typ.value_type
                while pa.types.is_fixed_size_list(typ):
                    typ = typ.value_type
                if (pa.types.is_list(typ) or pa.types.is_large_list(typ)
                        or pa.types.is_string(typ)
                        or pa.types.is_large_string(typ)
                        or pa.types.is_binary(typ)):
                    yield name, np.dtype(object)
                    return
                try:
                    yield name, np.dtype(typ.to_pandas_dtype())
                except (NotImplementedError, TypeError):
                    yield name, np.dtype(object)

            names, types = [], {}
            for n in top:
                for leaf, dt in leaves(n, arrow_schema.field(n).type):
                    names.append(leaf)
                    types[leaf] = dt
            # replay the op chain's projections over the flattened names
            for op in self._ops:
                if op[0] != "project":
                    continue
                st = op[1]
                if "select" in st:
                    names = [n for n in names if n in st["select"]]
                elif "drop" in st:
                    names = [n for n in names if n not in st["drop"]]
                elif "rename" in st:
                    names = [st["rename"].get(n, n) for n in names]
                    types = {st["rename"].get(n, n): t
                             for n, t in types.items()}
            return {n: types[n] for n in names}
        for ref in self._stream_refs():
            b = ray_tpu.get(ref)
            if _block_len(b):
                if isinstance(b, dict):
                    return {k: v.dtype for k, v in b.items()}
                r = _block_rows(b)[0]
                return {k: type(v) for k, v in r.items()} if isinstance(r, dict) else {
                    "value": type(r)}
        return None

    def num_blocks(self) -> int:
        if self._refs is None:
            return len(self._source.paths)  # known without reading
        return len(self._refs)

    def iter_rows(self) -> Iterator[Any]:
        for ref in self._stream_refs():
            yield from _block_rows(ray_tpu.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Block]:
        """Stream batches; a bounded window of block tasks executes ahead of
        consumption (backpressure — consumption drives submission)."""
        carry: Optional[Block] = None
        for ref in self._stream_refs():
            block = ray_tpu.get(ref)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            i = 0
            while n - i >= batch_size:
                yield _slice_block(block, i, i + batch_size)
                i += batch_size
            if i < n:
                carry = _slice_block(block, i, n)
        if carry is not None and not drop_last:
            yield carry

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False) -> Iterator[Dict[str, Any]]:
        """Batches as dicts of torch tensors (reference
        `Datastream.iter_torch_batches`). Non-numeric columns pass through
        unchanged; `dtypes` maps column -> torch dtype."""
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            yield _to_torch_batch(batch, dtypes, device)

    def stats(self) -> str:
        """Execute the pending op chain with per-operator timing and return
        a summary (reference `Dataset.stats()`): per op kind — total wall
        time across blocks, min/max per block, rows out."""
        timed = ray_tpu.remote(_apply_ops_timed)
        outs = ray_tpu.get([timed.remote(r, self._physical_ops)
                            for r in self._block_refs])
        per_op: Dict[int, List[float]] = {}
        total_rows = 0
        for block, timings in outs:
            total_rows += _block_len(block)
            for idx, _kind, seconds in timings:
                per_op.setdefault(idx, []).append(seconds)
        lines = [f"Datastream stats: {len(self._block_refs)} blocks, "
                 f"{total_rows} rows out"]
        for i, op in enumerate(self._physical_ops):
            kind = op[0]
            times = per_op.get(i, [])
            if not times:
                continue
            lines.append(
                f"  op {i} {kind}: total {sum(times)*1e3:.1f}ms, "
                f"min {min(times)*1e3:.2f}ms, max {max(times)*1e3:.2f}ms, "
                f"avg {np.mean(times)*1e3:.2f}ms over {len(times)} blocks")
        if not self._ops:
            lines.append("  (no pending ops — fully materialized)")
        return "\n".join(lines)

    def split(self, n: int, *, equal: bool = False) -> List["Datastream"]:
        refs = self._executed_refs()
        if equal:
            blocks = ray_tpu.get(refs)
            whole = _concat_blocks(blocks)
            total = _block_len(whole)
            per = total // n
            return [Datastream([ray_tpu.put(_slice_block(whole, i * per, (i + 1) * per))])
                    for i in builtins.range(n)]
        out: List[List[ObjectRef]] = [[] for _ in builtins.range(n)]
        for i, r in enumerate(refs):
            out[i % n].append(r)
        return [Datastream(r) for r in out]

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Per-consumer iterators fed by a coordinator actor (SURVEY §H).
        Block tasks execute lazily inside the coordinator as consumers pull
        (one block of prefetch per consumer) — the full pipeline output is
        never resident at once."""
        coord = _SplitCoordinator.options(num_cpus=0).remote(
            list(self._block_refs), n, list(self._physical_ops))
        return [DataIterator(coord, i) for i in builtins.range(n)]

    def __repr__(self):
        # num_blocks(), NOT _block_refs: printing a lazy stream (a REPL
        # echo!) must never launch the distributed read
        return (f"Datastream(num_blocks={self.num_blocks()}, "
                f"pending_ops={len(self._ops)})")


Dataset = Datastream  # the reference renamed Dataset->Datastream in this era


class ActorPoolStrategy:
    """Actor compute for stateful map_batches UDFs (reference
    python/ray/data/_internal/compute.py ActorPoolStrategy). `actor_options`
    pass through to `.options()` — e.g. {"resources": {"TPU": 1}} pins each
    pool member to a chip."""

    def __init__(self, min_size: int = 1, max_size: int = 4,
                 actor_options: Optional[Dict[str, Any]] = None):
        self.min_size = min_size
        self.max_size = max(min_size, max_size)
        self.actor_options = dict(actor_options or {})


def _to_torch_batch(batch: Block, dtypes, device: str) -> Dict[str, Any]:
    import torch

    if isinstance(batch, list):
        batch = _rows_to_block(batch)
        if isinstance(batch, list):  # non-dict rows: single "data" column
            batch = {"data": np.asarray(batch)}
    out: Dict[str, Any] = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        if arr.dtype.kind in "biuf":
            t = torch.as_tensor(arr)
            if dtypes and k in dtypes:
                t = t.to(dtypes[k])
            out[k] = t.to(device) if device != "cpu" else t
        else:
            out[k] = v
    return out


def _block_col(block: Block, col: str) -> Optional[np.ndarray]:
    if _block_len(block) == 0:
        return None
    if isinstance(block, dict):
        return np.asarray(block[col])
    vals = [r[col] for r in _block_rows(block)]
    try:
        return np.asarray(vals)
    except ValueError:  # ragged values (per-row lists): keep them as rows
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            out[i] = v
        return out


def _block_col_sum(block: Block, col: str):
    v = _block_col(block, col)
    return None if v is None else v.sum()


def _block_col_min(block: Block, col: str):
    v = _block_col(block, col)
    return None if v is None else v.min()


def _block_col_max(block: Block, col: str):
    v = _block_col(block, col)
    return None if v is None else v.max()


def _block_col_sum_count(block: Block, col: str):
    v = _block_col(block, col)
    return None if v is None else (v.sum(), len(v))


def _write_block_json(block: Block, path: str) -> None:
    import json

    with open(path, "w") as f:
        for r in _block_rows(block):
            if isinstance(r, dict):
                r = {k: (v.item() if isinstance(v, np.generic) else
                         v.tolist() if isinstance(v, np.ndarray) else v)
                     for k, v in r.items()}
            f.write(json.dumps(r) + "\n")


def _write_block_csv(block: Block, path: str) -> None:
    import csv

    rows = _block_rows(block)
    with open(path, "w", newline="") as f:
        if not rows:
            return
        if isinstance(rows[0], dict):
            w = csv.DictWriter(f, fieldnames=list(rows[0]))
            w.writeheader()
            for r in rows:
                w.writerow({k: (v.item() if isinstance(v, np.generic) else v)
                            for k, v in r.items()})
        else:
            w = csv.writer(f)
            for r in rows:
                w.writerow([r])


def _tensor_to_arrow(arr: np.ndarray):
    """Multi-dim numpy -> (nested) FixedSizeList arrow array, so tensor
    columns (e.g. [N, obs_dim] observations) round-trip through parquet
    (reference ArrowTensorArray, python/ray/air/util/tensor_extensions)."""
    import pyarrow as pa

    out = pa.array(arr.reshape(-1))
    for dim in reversed(arr.shape[1:]):
        out = pa.FixedSizeListArray.from_arrays(out, dim)
    return out


def _arrow_to_numpy(column) -> np.ndarray:
    """Arrow column -> numpy; (nested) FixedSizeList columns reassemble to
    a contiguous [N, ...] tensor instead of degrading to object arrays;
    dictionary-encoded columns decode to their values; variable-length
    lists become object arrays of per-row numpy arrays (lossless)."""
    import pyarrow as pa

    col = column.combine_chunks() if hasattr(column, "combine_chunks") \
        else column
    if pa.types.is_dictionary(col.type):
        col = col.dictionary_decode()
    shape = [len(col)]
    typ = col.type
    while pa.types.is_fixed_size_list(typ):
        shape.append(typ.list_size)
        typ = typ.value_type
    if len(shape) > 1:
        flat = col
        while hasattr(flat, "flatten") and pa.types.is_fixed_size_list(
                flat.type):
            flat = flat.flatten()
        return flat.to_numpy(zero_copy_only=False).reshape(shape)
    if pa.types.is_list(typ) or pa.types.is_large_list(typ):
        out = np.empty(len(col), dtype=object)
        for i, item in enumerate(col):
            out[i] = (None if not item.is_valid
                      else np.asarray(item.as_py()))
        return out
    return col.to_numpy(zero_copy_only=False)


def _table_to_block(table) -> Block:
    """Arrow table -> dict-of-numpy block, losslessly: struct columns
    flatten to dotted ``parent.child`` keys (the reference keeps structs
    arrow-side in ArrowBlockAccessor; the TPU-native block model is
    columnar numpy — device-feedable — so structs decompose instead of
    degrading to object arrays)."""
    import pyarrow as pa

    out: Dict[str, np.ndarray] = {}

    def add(name: str, col):
        chunked = col.combine_chunks() if hasattr(col, "combine_chunks") \
            else col
        if pa.types.is_struct(chunked.type):
            for field in chunked.type:
                add(f"{name}.{field.name}", chunked.field(field.name))
        else:
            out[name] = _arrow_to_numpy(chunked)

    for c in table.column_names:
        add(c, table[c])
    return out


def _write_block_parquet(block: Block, path: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    if isinstance(block, dict):
        cols = {}
        for k, v in block.items():
            v = np.asarray(v)
            cols[k] = _tensor_to_arrow(v) if v.ndim > 1 else pa.array(v)
        table = pa.table(cols)
    else:
        rows = _block_rows(block)
        cols = {k: [r[k] for r in rows] for k in (rows[0] if rows else {})}
        table = pa.table(cols)
    pq.write_table(table, path)


def _reverse_block(block: Block) -> Block:
    if isinstance(block, dict):
        return {k: np.asarray(v)[::-1].copy() for k, v in block.items()}
    return list(reversed(_block_rows(block)))


def _zip_merge(a_block: Block, ranges: List[tuple], *b_blocks: Block) -> Block:
    pieces = [_slice_block(b, s, e) for b, (s, e) in zip(b_blocks, ranges)]
    b_all = _concat_blocks(pieces) if pieces else []
    rows_a = _block_rows(a_block)
    rows_b = _block_rows(b_all)
    merged = []
    for ra, rb in zip(rows_a, rows_b):
        ra = ra if isinstance(ra, dict) else {"value": ra}
        rb = rb if isinstance(rb, dict) else {"value_1": rb}
        m = dict(ra)
        for k, v in rb.items():
            m[k if k not in m else f"{k}_1"] = v
        merged.append(m)
    return _rows_to_block(merged)




class GroupedData:
    """Result of `Datastream.groupby`: per-key aggregations over
    hash-co-located partitions (reference `python/ray/data/grouped_data.py`)."""

    def __init__(self, block_refs: List[ObjectRef], key):
        self._refs = block_refs
        self._key = key

    def _agg(self, init, accum, col: Optional[str], out_name: str) -> Datastream:
        key = self._key

        def agg_block(block: Block) -> Block:
            from ray_tpu.data.shuffle import _key_values

            n = _block_len(block)
            if n == 0:
                return []
            kv = _key_values(block, key)
            rows = _block_rows(block)
            groups: Dict[Any, Any] = {}
            for i in builtins.range(n):
                k = kv[i].item() if hasattr(kv[i], "item") else kv[i]
                v = rows[i][col] if col is not None else rows[i]
                groups[k] = accum(groups.get(k, init), v)
            gname = key if isinstance(key, str) else "key"
            return _rows_to_block(
                [{gname: k, out_name: v} for k, v in groups.items()])

        task = ray_tpu.remote(agg_block)
        return Datastream([task.remote(r) for r in self._refs])

    def count(self) -> Datastream:
        return self._agg(0, lambda acc, _: acc + 1, None, "count()")

    def sum(self, col: str) -> Datastream:
        return self._agg(0, lambda acc, v: acc + v, col, f"sum({col})")

    def min(self, col: str) -> Datastream:
        return self._agg(float("inf"), lambda acc, v: builtins.min(acc, v),
                         col, f"min({col})")

    def max(self, col: str) -> Datastream:
        return self._agg(float("-inf"), lambda acc, v: builtins.max(acc, v),
                         col, f"max({col})")

    def mean(self, col: str) -> Datastream:
        summed = self._agg((0.0, 0), lambda acc, v: (acc[0] + v, acc[1] + 1),
                           col, "_sc")
        gname = self._key if isinstance(self._key, str) else "key"

        def finish(row):
            s, c = row["_sc"]
            return {gname: row[gname], f"mean({col})": s / builtins.max(c, 1)}

        return summed.map(finish)

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Datastream:
        key = self._key

        def apply(block: Block) -> Block:
            from ray_tpu.data.shuffle import _key_values

            n = _block_len(block)
            if n == 0:
                return []
            kv = _key_values(block, key)
            rows = _block_rows(block)
            groups: Dict[Any, List[Any]] = {}
            for i in builtins.range(n):
                k = kv[i].item() if hasattr(kv[i], "item") else kv[i]
                groups.setdefault(k, []).append(rows[i])
            out: List[Any] = []
            for g in groups.values():
                res = fn(g)
                out.extend(res if isinstance(res, list) else [res])
            return _rows_to_block(out)

        task = ray_tpu.remote(apply)
        return Datastream([task.remote(r) for r in self._refs])


@ray_tpu.remote
class _SplitCoordinator:
    """Serves block refs round-robin to n consumers, epoch-synchronized.

    Blocks with pending ops execute lazily on demand (reference
    StreamSplitDataIterator over the streaming executor,
    `stream_split_iterator.py:41`): each next_block submits the consumer's
    block if needed plus one block of prefetch, so at most ~2 executed
    blocks per consumer are resident at a time."""

    def __init__(self, refs: List[ObjectRef], n: int, ops: Optional[list] = None):
        self.refs = refs
        self.n = n
        self.ops = list(ops or [])
        self.epoch_positions: Dict[int, int] = {}
        self._prefetched: Dict[int, Any] = {}  # pos -> executed block ref

    def _executed(self, pos: int):
        if not self.ops:
            return self.refs[pos]
        ref = self._prefetched.pop(pos, None)
        if ref is None:
            ref = _exec_block.remote(self.refs[pos], self.ops)
        return ref

    def next_block(self, consumer: int):
        pos = self.epoch_positions.get(consumer, consumer)
        if pos >= len(self.refs):
            return None
        self.epoch_positions[consumer] = pos + self.n
        ref = self._executed(pos)
        nxt = pos + self.n
        if self.ops and nxt < len(self.refs) and nxt not in self._prefetched:
            self._prefetched[nxt] = _exec_block.remote(self.refs[nxt], self.ops)
        return ref

    def reset(self, consumer: int):
        self.epoch_positions[consumer] = consumer
        return True


class DataIterator:
    """Per-worker view of a streaming split (cf. reference DataIterator)."""

    def __init__(self, coordinator, index: int):
        self._coord = coordinator
        self._index = index

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Block]:
        ray_tpu.get(self._coord.reset.remote(self._index))
        carry: Optional[Block] = None
        while True:
            ref = ray_tpu.get(self._coord.next_block.remote(self._index))
            if ref is None:
                break
            block = ray_tpu.get(ref)
            if carry is not None:
                block = _concat_blocks([carry, block])
                carry = None
            n = _block_len(block)
            i = 0
            while n - i >= batch_size:
                yield _slice_block(block, i, i + batch_size)
                i += batch_size
            if i < n:
                carry = _slice_block(block, i, n)
        if carry is not None and not drop_last:
            yield carry

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False) -> Iterator[Dict[str, Any]]:
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            yield _to_torch_batch(batch, dtypes, device)

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=256):
            yield from _block_rows(batch)

    def __reduce__(self):
        return (DataIterator, (self._coord, self._index))


# ------------------------------------------------------------ constructors


def from_items(items: List[Any], *, parallelism: int = 8) -> Datastream:
    n = max(1, min(parallelism, len(items) or 1))
    per = -(-len(items) // n) if items else 1
    refs = [ray_tpu.put(_rows_to_block(items[i:i + per]))
            for i in builtins.range(0, max(len(items), 1), per)]
    return Datastream(refs)


def range(n: int, *, parallelism: int = 8) -> Datastream:  # noqa: A001
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        refs.append(ray_tpu.put({"id": np.arange(start, end)}))
    return Datastream(refs)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Datastream:
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        ids = np.arange(start, end)
        data = np.broadcast_to(ids.reshape(-1, *([1] * len(shape))),
                               (end - start, *shape)).copy()
        refs.append(ray_tpu.put({"data": data}))
    return Datastream(refs)


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]],
               *, parallelism: int = 8) -> Datastream:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    n = len(next(iter(arrays.values())))
    per = -(-n // parallelism) if n else 1
    refs = []
    for start in builtins.range(0, max(n, 1), per):
        end = min(start + per, n)
        refs.append(ray_tpu.put({k: v[start:end] for k, v in arrays.items()}))
    return Datastream(refs)


def read_text(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        with open(path) as f:
            return [{"text": line.rstrip("\n")} for line in f]

    return Datastream([load.remote(p) for p in paths])


def read_json(paths: Union[str, List[str]]) -> Datastream:
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        import json

        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return _rows_to_block(rows)

    return Datastream([load.remote(p) for p in paths])


@ray_tpu.remote
def _load_csv(path: str, columns, filters) -> Block:
    import csv

    with open(path) as f:
        rows = [dict(r) for r in csv.DictReader(f)]
    if columns:
        rows = [{c: r[c] for c in columns} for r in rows]
    return _rows_to_block(rows)


def read_csv(paths: Union[str, List[str]], *,
             columns: Optional[List[str]] = None) -> Datastream:
    """CSV read; `columns` (given or pushed down from a later select)
    prunes parsed columns at the reader."""
    paths = [paths] if isinstance(paths, str) else list(paths)
    return Datastream(None, source=_SourceSpec(
        "csv", paths, _load_csv, supports_columns=True, columns=columns))


@ray_tpu.remote
def _load_parquet(path: str, columns, filters) -> Block:
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns, filters=filters)
    return _table_to_block(table)


def read_parquet(paths: Union[str, List[str]], *,
                 columns: Optional[List[str]] = None,
                 filters: Optional[list] = None) -> Datastream:
    """Parquet read with FILE-LAYER pruning (reference
    parquet_datasource.py:179,214): `columns` decodes only those columns;
    `filters` ([(col, op, value), ...]) prunes row groups by statistics
    before decoding. Both also arrive automatically via pushdown from
    later `select_columns`/`filter(col(...) ...)` calls — the read is
    lazy until blocks are first consumed."""
    paths = [paths] if isinstance(paths, str) else list(paths)
    return Datastream(None, source=_SourceSpec(
        "parquet", paths, _load_parquet, supports_columns=True,
        supports_filters=True, columns=columns, filters=filters))


def read_numpy(paths: Union[str, List[str]]) -> Datastream:
    """.npy files, one tensor column per file (reference numpy datasource)."""
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        return {"data": np.load(path)}

    return Datastream([load.remote(p) for p in paths])


def read_binary_files(paths: Union[str, List[str]],
                      include_paths: bool = False) -> Datastream:
    """Raw bytes per file (reference binary datasource)."""
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        row = {"bytes": data}
        if include_paths:
            row["path"] = path
        return [row]

    return Datastream([load.remote(p) for p in paths])


def read_tfrecords(paths: Union[str, List[str]]) -> Datastream:
    """tf.train.Example TFRecord files, decoded without a TF dependency
    (ray_tpu.data.tfrecord; reference tfrecords_datasource.py). Scalar
    features unwrap to scalars, multi-element ones stay arrays/lists."""
    paths = [paths] if isinstance(paths, str) else list(paths)

    @ray_tpu.remote
    def load(path: str) -> Block:
        from ray_tpu.data.tfrecord import decode_example, read_records

        rows = []
        for rec in read_records(path):
            row = {}
            for k, v in decode_example(rec).items():
                if len(v) == 1:
                    v = v[0]
                row[k] = v
            rows.append(row)
        return _rows_to_block(rows)

    return Datastream([load.remote(p) for p in paths])


def _write_block_tfrecords(block: Block, path: str) -> None:
    from ray_tpu.data.tfrecord import encode_example, write_records

    write_records(path, [encode_example(
        {k: v for k, v in row.items()}) for row in _block_rows(block)])


def from_pandas(dfs) -> Datastream:
    """One block per DataFrame (reference ray.data.from_pandas)."""
    import pandas as pd

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    return Datastream([
        ray_tpu.put({c: df[c].to_numpy() for c in df.columns}) for df in dfs])


def from_arrow(tables) -> Datastream:
    """One block per pyarrow Table (reference ray.data.from_arrow)."""
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    return Datastream([
        ray_tpu.put(_table_to_block(t))
        for t in tables])


