"""TFRecord IO: pure-Python codec, no TensorFlow dependency.

Parity with the reference's tfrecords datasource
(`python/ray/data/datasource/tfrecords_datasource.py`, which imports
tensorflow): the wire format is implemented directly — length-delimited
records with masked CRC32C framing, and a hand-rolled encoder/decoder for
the stable `tf.train.Example` protobuf schema (features: map<string,
Feature>; Feature: oneof {bytes_list, float_list, int64_list}).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# ------------------------------------------------------------------ crc32c

_CRC_TABLE: List[int] = []


def _make_table() -> None:
    poly = 0x82F63B78  # Castagnoli, reflected
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------------- record frame


def write_records(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            hdr = struct.pack("<Q", len(rec))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                return
            (length,) = struct.unpack("<Q", hdr)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(hdr):
                raise ValueError(f"{path}: corrupt length header")
            rec = f.read(length)
            (rcrc,) = struct.unpack("<I", f.read(4))
            if rcrc != _masked_crc(rec):
                raise ValueError(f"{path}: corrupt record payload")
            yield rec


# --------------------------------------------------- tf.train.Example codec
# Minimal protobuf wire codec for the fixed Example schema:
#   Example{ features: Features=1 }  Features{ feature: map<str,Feature>=1 }
#   Feature{ bytes_list=1 | float_list=2 | int64_list=3 }
#   BytesList{ value: repeated bytes=1 }   FloatList{ value: repeated float=1 }
#   Int64List{ value: repeated int64=1 }


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _len_field(field_no: int, payload: bytes) -> bytes:
    return _varint(field_no << 3 | 2) + _varint(len(payload)) + payload


def _encode_feature(values: Any) -> bytes:
    arr = np.asarray(values)
    if arr.dtype.kind in ("S", "U", "O") or isinstance(values, (bytes, str)):
        items = values if isinstance(values, (list, tuple, np.ndarray)) else [values]
        payload = b"".join(
            _len_field(1, v.encode() if isinstance(v, str) else bytes(v))
            for v in items)
        return _len_field(1, payload)  # bytes_list
    if arr.dtype.kind == "f":
        payload = _varint(1 << 3 | 2) + _varint(4 * arr.size) + \
            arr.astype("<f4").tobytes()  # packed floats
        return _len_field(2, payload)
    payload = b"".join(_varint(1 << 3 | 0) + _varint(int(v) & (2**64 - 1))
                       for v in arr.reshape(-1))
    return _len_field(3, payload)  # int64_list


def encode_example(features: Dict[str, Any]) -> bytes:
    feats = b""
    for name, values in features.items():
        key = _len_field(1, name.encode())
        val = _len_field(2, _encode_feature(values))
        feats += _len_field(1, key + val)  # map entry
    return _len_field(1, feats)  # Example.features


def _decode_feature(buf: bytes):
    tag, pos = _read_varint(buf, 0)
    field = tag >> 3
    ln, pos = _read_varint(buf, pos)
    payload = buf[pos:pos + ln]
    if field == 1:  # bytes_list
        out = []
        p = 0
        while p < len(payload):
            _, p = _read_varint(payload, p)   # tag (field 1, wire 2)
            sz, p = _read_varint(payload, p)
            out.append(payload[p:p + sz])
            p += sz
        return out
    if field == 2:  # float_list (packed or unpacked)
        out = []
        p = 0
        while p < len(payload):
            t, p = _read_varint(payload, p)
            if t & 7 == 2:  # packed
                sz, p = _read_varint(payload, p)
                out.extend(np.frombuffer(payload, "<f4", sz // 4, p).tolist())
                p += sz
            else:  # single fixed32
                out.append(struct.unpack_from("<f", payload, p)[0])
                p += 4
        return np.asarray(out, np.float32)
    # int64_list
    out = []
    p = 0
    while p < len(payload):
        t, p = _read_varint(payload, p)
        if t & 7 == 2:  # packed
            sz, p = _read_varint(payload, p)
            end = p + sz
            while p < end:
                v, p = _read_varint(payload, p)
                out.append(v - 2**64 if v >= 2**63 else v)
        else:
            v, p = _read_varint(payload, p)
            out.append(v - 2**64 if v >= 2**63 else v)
    return np.asarray(out, np.int64)


def decode_example(data: bytes) -> Dict[str, Any]:
    # unwrap Example.features
    tag, pos = _read_varint(data, 0)
    assert tag >> 3 == 1, "not an Example"
    ln, pos = _read_varint(data, pos)
    feats = data[pos:pos + ln]
    out: Dict[str, Any] = {}
    p = 0
    while p < len(feats):
        _, p = _read_varint(feats, p)       # map-entry tag
        entry_len, p = _read_varint(feats, p)
        entry = feats[p:p + entry_len]
        p += entry_len
        ep = 0
        name, value = "", None
        while ep < len(entry):
            etag, ep = _read_varint(entry, ep)
            eln, ep = _read_varint(entry, ep)
            payload = entry[ep:ep + eln]
            ep += eln
            if etag >> 3 == 1:
                name = payload.decode()
            else:
                value = _decode_feature(payload)
        out[name] = value
    return out
