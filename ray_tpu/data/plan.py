"""Logical plan + optimizer passes for Datastream.

Mirrors the reference's logical-plan layer (`python/ray/data/_internal/
logical/`): transforms append LOGICAL operators; before execution the chain
runs through rule passes, then LOWERS to the physical fused-op list the
block executor runs. Rules are small, unit-testable rewrites — fusion and
pushdowns are explicit passes, not side effects of how transforms happen to
be recorded.

Logical operators (tuples, like the physical ops they extend):
  ("map", fn) ("flat_map", fn) ("filter", fn) ("map_batches", fn)
  ("project", {"select": [..]} | {"drop": [..]} | {"rename": {..}})
  ("filter_expr", ColumnPredicate)   # pushable into parquet readers
  ("limit", n)

Passes:
  ProjectionFusion  — adjacent projections collapse into one (a
                      select+rename+drop chain becomes a single block pass)
  LimitPushdown     — a limit hops backwards over 1:1 row-preserving ops
                      (map / project), so expensive UDFs run on at most n
                      rows instead of whole blocks
  CountProjection   — used by count(): trailing count-preserving ops are
                      dropped entirely (a map-only chain counts SOURCE
                      blocks without running any UDF)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

__all__ = ["optimize", "lower", "ops_for_count", "explain_ops",
           "ROW_PRESERVING"]

# ops that neither add nor remove rows (1:1): limits and counts commute
ROW_PRESERVING = frozenset({"map", "project"})


# ------------------------------------------------------------------- rules


def _fuse_projections(ops: List[tuple]) -> Tuple[List[tuple], bool]:
    """Merge adjacent ("project", spec) ops into one composite spec."""
    out: List[tuple] = []
    changed = False
    for op in ops:
        if op[0] == "project" and out and out[-1][0] == "project":
            out[-1] = ("project", _compose_projections(out[-1][1], op[1]))
            changed = True
        else:
            out.append(op)
    return out, changed


def _compose_projections(first: Dict[str, Any],
                         second: Dict[str, Any]) -> Dict[str, Any]:
    """One spec equivalent to applying `first` then `second`. Specs are
    kept as an ordered STEP LIST under "steps" once composed (projection
    algebra over arbitrary select/drop/rename chains is simplest as a
    pipeline; the win is one block pass + one op slot, and further rules
    see a single op)."""
    steps = list(first.get("steps") or [first])
    steps += list(second.get("steps") or [second])
    return {"steps": steps}


def _limit_pushdown(ops: List[tuple]) -> Tuple[List[tuple], bool]:
    """Move each limit before any immediately-preceding row-preserving op:
    [map, limit n] == [limit n, map] with the map touching <= n rows."""
    ops = list(ops)
    changed = False
    for i in range(1, len(ops)):
        if ops[i][0] == "limit" and ops[i - 1][0] in ROW_PRESERVING:
            ops[i - 1], ops[i] = ops[i], ops[i - 1]
            changed = True
    return ops, changed


_RULES: List[Tuple[str, Callable[[List[tuple]], Tuple[List[tuple], bool]]]] = [
    ("ProjectionFusion", _fuse_projections),
    ("LimitPushdown", _limit_pushdown),
]


def optimize(ops: List[tuple]) -> Tuple[List[tuple], List[str]]:
    """Run rule passes to fixpoint; returns (ops, applied rule names)."""
    applied: List[str] = []
    for _ in range(len(ops) + 2):  # fixpoint bound: each pass strictly shrinks/reorders
        any_change = False
        for name, rule in _RULES:
            ops, changed = rule(ops)
            if changed:
                any_change = True
                if name not in applied:
                    applied.append(name)
        if not any_change:
            break
    return ops, applied


def ops_for_count(ops: List[tuple]) -> Tuple[List[tuple], bool]:
    """CountProjection: drop trailing count-preserving ops — counting rows
    needs only the prefix that can change row counts. Returns (ops,
    applied)."""
    n = len(ops)
    while n > 0 and ops[n - 1][0] in ROW_PRESERVING:
        n -= 1
    return list(ops[:n]), n != len(ops)


# ------------------------------------------------------------------ lower


def _project_fn(spec: Dict[str, Any]) -> Callable:
    steps = spec.get("steps") or [spec]

    def run(block):
        for st in steps:
            if "select" in st:
                keep = st["select"]
                block = {k: block[k] for k in keep}
            elif "drop" in st:
                dropped = set(st["drop"])
                block = {k: v for k, v in block.items() if k not in dropped}
            elif "rename" in st:
                m = st["rename"]
                block = {m.get(k, k): v for k, v in block.items()}
        return block

    return run


def lower(ops: List[tuple]) -> List[tuple]:
    """Logical -> physical: projections become one batched block fn; the
    executor-side kinds (map/map_batches/flat_map/filter/limit) pass
    through."""
    out: List[tuple] = []
    for op in ops:
        if op[0] == "project":
            out.append(("map_batches", _project_fn(op[1])))
        else:
            out.append(op)
    return out


# ----------------------------------------------------------------- explain


def _op_label(op: tuple) -> str:
    kind = op[0]
    if kind == "project":
        spec = op[1]
        steps = spec.get("steps") or [spec]
        return "Project[%s]" % "+".join(next(iter(s)) for s in steps)
    if kind == "filter_expr":
        return f"Filter[{op[1]!r}]"
    if kind == "limit":
        return f"Limit[{op[1]}]"
    fn = op[1]
    name = getattr(fn, "__name__", type(fn).__name__)
    return f"{kind.title().replace('_', '')}({name})"


def explain_ops(num_blocks: int, logical: List[tuple],
                source_desc: str = None) -> str:
    optimized, applied = optimize(list(logical))
    physical = lower(optimized)
    lines = [source_desc or f"Source[{num_blocks} blocks]"]
    lines += [f"  -> {_op_label(op)}" for op in logical]
    lines.append("Optimized (rules: %s):" % (", ".join(applied) or "none"))
    lines += [f"  -> {_op_label(op)}" for op in optimized]
    lines.append("Physical ops: [%s]" % ", ".join(op[0] for op in physical))
    return "\n".join(lines)
