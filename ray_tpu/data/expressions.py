"""Column predicate expressions for filter pushdown.

`col("x") > 5` builds a `ColumnPredicate` that executes BOTH ways: as a
vectorized mask over columnar blocks in the executor, and as a
`(column, op, value)` tuple pushed into parquet readers where pyarrow
prunes row groups by statistics before decoding (reference
`python/ray/data/datasource/parquet_datasource.py:214` filter pushdown,
`pyarrow.parquet.read_table(filters=...)`)."""

from __future__ import annotations

from typing import Any

import numpy as np

__all__ = ["col", "ColumnPredicate"]

_OPS = {
    ">": np.greater, ">=": np.greater_equal,
    "<": np.less, "<=": np.less_equal,
    "==": np.equal, "!=": np.not_equal,
}


class ColumnPredicate:
    """One comparison against a column; AND by chaining .filter() calls."""

    def __init__(self, column: str, op: str, value: Any):
        if op not in _OPS:
            raise ValueError(f"unsupported predicate op {op!r}")
        self.column = column
        self.op = op
        self.value = value

    def as_tuple(self):
        """pyarrow read_table(filters=...) form."""
        return (self.column, "=" if self.op == "==" else self.op, self.value)

    def mask(self, block: dict) -> np.ndarray:
        return _OPS[self.op](np.asarray(block[self.column]), self.value)

    def __call__(self, row: dict) -> bool:
        return bool(_OPS[self.op](row[self.column], self.value))

    def __repr__(self):
        return f"col({self.column!r}) {self.op} {self.value!r}"


class _Col:
    def __init__(self, name: str):
        self._name = name

    def __gt__(self, v):
        return ColumnPredicate(self._name, ">", v)

    def __ge__(self, v):
        return ColumnPredicate(self._name, ">=", v)

    def __lt__(self, v):
        return ColumnPredicate(self._name, "<", v)

    def __le__(self, v):
        return ColumnPredicate(self._name, "<=", v)

    def __eq__(self, v):  # noqa: E501 — expression builder, not identity
        return ColumnPredicate(self._name, "==", v)

    def __ne__(self, v):
        return ColumnPredicate(self._name, "!=", v)

    __hash__ = None


def col(name: str) -> _Col:
    """Column reference for predicate expressions: `col("x") > 5`."""
    return _Col(name)
