"""Preprocessors: fit/transform over Datastreams.

Capability parity with the reference's `python/ray/data/preprocessors/`
(scalers, encoders, chain, batch mapper, concatenator). Fit statistics are
computed with distributed column reductions; transform is a lazy
`map_batches` so it fuses into the block tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.datastream import Datastream


class Preprocessor:
    """fit(ds) learns state; transform(ds) applies it lazily."""

    _fitted = False

    def fit(self, ds: Datastream) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds: Datastream) -> Datastream:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self._transform_batch)

    def fit_transform(self, ds: Datastream) -> Datastream:
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return self._transform_batch(batch)

    # -- subclass hooks
    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds: Datastream) -> None:
        pass

    def _transform_batch(self, batch):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference `preprocessors/scaler.py`)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.stats[c] = (ds.mean(c), ds.std(c, ddof=0) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats[c] = (float(lo), float(hi))

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats[c]
            rng = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / rng
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.column = label_column
        self.classes: List[Any] = []

    def _fit(self, ds: Datastream) -> None:
        self.classes = ds.unique(self.column)
        self._index = {c: i for i, c in enumerate(self.classes)}

    def _transform_batch(self, batch):
        out = dict(batch)
        out[self.column] = np.asarray(
            [self._index[v.item() if hasattr(v, "item") else v]
             for v in np.atleast_1d(batch[self.column])])
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.classes: Dict[str, List[Any]] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.classes[c] = ds.unique(c)

    def _transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vals = np.atleast_1d(batch[c])
            for cls in self.classes[c]:
                out[f"{c}_{cls}"] = (vals == cls).astype(np.int64)
        return out


class Concatenator(Preprocessor):
    """Pack feature columns into one float matrix column (the layout
    `iter_batches` feeds straight to `jax.device_put`)."""

    def __init__(self, include: Optional[List[str]] = None,
                 exclude: Optional[List[str]] = None,
                 output_column_name: str = "concat_out",
                 dtype=np.float32):
        self.include = include
        self.exclude = set(exclude or [])
        self.out = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        cols = self.include or [k for k in batch if k not in self.exclude]
        mats = [np.asarray(batch[c], dtype=self.dtype).reshape(len(batch[c]), -1)
                for c in cols]
        out = {k: v for k, v in batch.items() if k not in cols}
        out[self.out] = np.concatenate(mats, axis=1)
        return out


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.stages = list(preprocessors)

    def fit(self, ds: Datastream) -> "Chain":
        for i, p in enumerate(self.stages):
            p.fit(ds)
            ds = p.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds: Datastream) -> Datastream:
        for p in self.stages:
            ds = p.transform(ds)
        return ds

    def _transform_batch(self, batch):
        for p in self.stages:
            batch = p._transform_batch(batch)
        return batch
