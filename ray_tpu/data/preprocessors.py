"""Preprocessors: fit/transform over Datastreams.

Capability parity with the reference's `python/ray/data/preprocessors/`
(scalers, encoders, chain, batch mapper, concatenator). Fit statistics are
computed with distributed column reductions; transform is a lazy
`map_batches` so it fuses into the block tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.datastream import Datastream, _block_rows


class Preprocessor:
    """fit(ds) learns state; transform(ds) applies it lazily."""

    _fitted = False

    def fit(self, ds: Datastream) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds: Datastream) -> Datastream:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        fn = self._transform_batch
        return ds.map_batches(lambda b: fn(_as_columns(b)))

    def fit_transform(self, ds: Datastream) -> Datastream:
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return self._transform_batch(_as_columns(batch))

    # -- subclass hooks
    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds: Datastream) -> None:
        pass

    def _transform_batch(self, batch):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference `preprocessors/scaler.py`)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.stats[c] = (ds.mean(c), ds.std(c, ddof=0) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            lo, hi = ds.min(c), ds.max(c)
            self.stats[c] = (float(lo), float(hi))

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats[c]
            rng = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / rng
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.column = label_column
        self.classes: List[Any] = []

    def _fit(self, ds: Datastream) -> None:
        self.classes = ds.unique(self.column)
        self._index = {c: i for i, c in enumerate(self.classes)}

    def _transform_batch(self, batch):
        out = dict(batch)
        out[self.column] = np.asarray(
            [self._index[v.item() if hasattr(v, "item") else v]
             for v in np.atleast_1d(batch[self.column])])
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.classes: Dict[str, List[Any]] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.classes[c] = ds.unique(c)

    def _transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vals = np.atleast_1d(batch[c])
            for cls in self.classes[c]:
                out[f"{c}_{cls}"] = (vals == cls).astype(np.int64)
        return out


class Concatenator(Preprocessor):
    """Pack feature columns into one float matrix column (the layout
    `iter_batches` feeds straight to `jax.device_put`)."""

    def __init__(self, include: Optional[List[str]] = None,
                 exclude: Optional[List[str]] = None,
                 output_column_name: str = "concat_out",
                 dtype=np.float32):
        self.include = include
        self.exclude = set(exclude or [])
        self.out = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        cols = self.include or [k for k in batch if k not in self.exclude]
        mats = [np.asarray(batch[c], dtype=self.dtype).reshape(len(batch[c]), -1)
                for c in cols]
        out = {k: v for k, v in batch.items() if k not in cols}
        out[self.out] = np.concatenate(mats, axis=1)
        return out


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.stages = list(preprocessors)

    def fit(self, ds: Datastream) -> "Chain":
        for i, p in enumerate(self.stages):
            p.fit(ds)
            ds = p.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds: Datastream) -> Datastream:
        for p in self.stages:
            ds = p.transform(ds)
        return ds

    def _transform_batch(self, batch):
        for p in self.stages:
            batch = p._transform_batch(batch)
        return batch


def _column_values(ds: Datastream, column: str) -> np.ndarray:
    """Gather one column to the driver for fit statistics that need the
    full distribution (quantiles, vocabularies). Extraction runs remotely
    per block (Datastream._column_values) — only the named column crosses
    the wire."""
    parts = [np.atleast_1d(v) for v in ds._column_values(column)
             if len(np.atleast_1d(v))]
    return np.concatenate(parts) if parts else np.array([])


class MaxAbsScaler(Preprocessor):
    """x / max|x| per column (reference `preprocessors/scaler.py:181`)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats: Dict[str, float] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.stats[c] = float(max(abs(ds.min(c)), abs(ds.max(c)))) or 1.0

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.asarray(batch[c], dtype=np.float64) / self.stats[c]
        return out


class RobustScaler(Preprocessor):
    """(x - median) / IQR per column — outlier-insensitive scaling
    (reference `preprocessors/scaler.py` RobustScaler)."""

    def __init__(self, columns: List[str],
                 quantile_range: tuple = (0.25, 0.75)):
        self.columns = list(columns)
        self.quantile_range = quantile_range
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Datastream) -> None:
        lo_q, hi_q = self.quantile_range
        for c in self.columns:
            vals = _column_values(ds, c).astype(np.float64)
            med = float(np.median(vals))
            lo, hi = np.quantile(vals, [lo_q, hi_q])
            self.stats[c] = (med, float(hi - lo) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            med, iqr = self.stats[c]
            out[c] = (np.asarray(batch[c], dtype=np.float64) - med) / iqr
        return out


class SimpleImputer(Preprocessor):
    """Fill missing values (NaN; None for object columns) with the fitted
    mean/median/most_frequent or a constant (reference
    `preprocessors/imputer.py`)."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = None):
        if strategy not in ("mean", "median", "most_frequent", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats: Dict[str, Any] = {}

    def _needs_fit(self) -> bool:
        return self.strategy != "constant"

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            vals = _column_values(ds, c)
            if self.strategy == "most_frequent":
                items, counts = np.unique(
                    vals[~_missing_mask(vals)], return_counts=True)
                self.stats[c] = items[np.argmax(counts)]
                continue
            clean = vals[~_missing_mask(vals)].astype(np.float64)
            self.stats[c] = (float(np.mean(clean)) if self.strategy == "mean"
                             else float(np.median(clean)))

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            vals = np.atleast_1d(batch[c])
            fill = (self.fill_value if self.strategy == "constant"
                    else self.stats[c])
            mask = _missing_mask(vals)
            if mask.any():
                vals = vals.copy()
                vals[mask] = fill
            out[c] = vals
        return out


def _as_columns(batch) -> Dict[str, np.ndarray]:
    """Row blocks (list-of-dicts with list-valued fields, e.g. from_items)
    columnarize to object arrays so every preprocessor sees one layout."""
    if isinstance(batch, dict):
        return batch
    rows = _block_rows(batch)
    if not (rows and isinstance(rows[0], dict)):
        return batch  # scalar rows: nothing columnar to offer
    out: Dict[str, np.ndarray] = {}
    for k in rows[0]:
        col = np.empty(len(rows), dtype=object)
        for i, r in enumerate(rows):
            col[i] = r.get(k)
        out[k] = col
    return out


def _missing_mask(vals: np.ndarray) -> np.ndarray:
    if vals.dtype.kind == "f":
        return np.isnan(vals)
    if vals.dtype == object:
        return np.asarray([v is None or (isinstance(v, float) and np.isnan(v))
                           for v in vals])
    return np.zeros(len(vals), dtype=bool)


class Normalizer(Preprocessor):
    """Row-wise normalization to unit l1/l2/max norm over a column group
    (reference `preprocessors/normalizer.py`)."""

    def __init__(self, columns: List[str], norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = list(columns)
        self.norm = norm

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        mat = np.stack([np.asarray(batch[c], dtype=np.float64)
                        for c in self.columns], axis=1)
        if self.norm == "l1":
            denom = np.abs(mat).sum(axis=1)
        elif self.norm == "l2":
            denom = np.sqrt((mat * mat).sum(axis=1))
        else:
            denom = np.abs(mat).max(axis=1)
        denom = np.where(denom == 0, 1.0, denom)
        out = dict(batch)
        for i, c in enumerate(self.columns):
            out[c] = mat[:, i] / denom
        return out


class PowerTransformer(Preprocessor):
    """Yeo-Johnson / Box-Cox power transform with a caller-chosen power
    (reference `preprocessors/transformer.py:43` — the reference also
    takes the power as a parameter rather than fitting it)."""

    def __init__(self, columns: List[str], power: float,
                 method: str = "yeo-johnson"):
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError(f"unknown method {method!r}")
        self.columns = list(columns)
        self.power = power
        self.method = method

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        out = dict(batch)
        p = self.power
        for c in self.columns:
            x = np.asarray(batch[c], dtype=np.float64)
            if self.method == "box-cox":
                out[c] = np.log(x) if p == 0 else (np.power(x, p) - 1) / p
                continue
            pos = x >= 0
            r = np.empty_like(x)
            r[pos] = (np.log1p(x[pos]) if p == 0
                      else (np.power(x[pos] + 1, p) - 1) / p)
            r[~pos] = (-np.log1p(-x[~pos]) if p == 2
                       else -(np.power(1 - x[~pos], 2 - p) - 1) / (2 - p))
            out[c] = r
        return out


class OrdinalEncoder(Preprocessor):
    """Category -> integer index per column (reference
    `preprocessors/encoder.py` OrdinalEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.index: Dict[str, Dict[Any, int]] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            self.index[c] = {v: i for i, v in enumerate(ds.unique(c))}

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            idx = self.index[c]
            out[c] = np.asarray(
                [idx[v.item() if hasattr(v, "item") else v]
                 for v in np.atleast_1d(batch[c])], dtype=np.int64)
        return out


class MultiHotEncoder(Preprocessor):
    """List-valued column -> fixed multi-hot vector (reference
    `preprocessors/encoder.py` MultiHotEncoder): pairs with the arrow
    ingestion that keeps var-length list columns as per-row arrays."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.classes: Dict[str, List[Any]] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            seen = set()
            for row_list in _column_values(ds, c):
                seen.update(np.asarray(row_list).tolist())
            self.classes[c] = sorted(seen)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            idx = {v: i for i, v in enumerate(self.classes[c])}
            rows = np.atleast_1d(batch[c])
            mat = np.zeros((len(rows), len(idx)), dtype=np.int64)
            for i, row_list in enumerate(rows):
                for v in np.asarray(row_list).tolist():
                    if v in idx:
                        mat[i, idx[v]] = 1
            out[c] = mat
        return out


class KBinsDiscretizer(Preprocessor):
    """Continuous column -> integer bin ids, uniform or quantile edges
    (reference `preprocessors/discretizer.py` Uniform/CustomKBins)."""

    def __init__(self, columns: List[str], bins: int = 5,
                 strategy: str = "uniform"):
        if strategy not in ("uniform", "quantile"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.columns = list(columns)
        self.bins = bins
        self.strategy = strategy
        self.edges: Dict[str, np.ndarray] = {}

    def _fit(self, ds: Datastream) -> None:
        for c in self.columns:
            if self.strategy == "uniform":
                lo, hi = float(ds.min(c)), float(ds.max(c))
                self.edges[c] = np.linspace(lo, hi, self.bins + 1)[1:-1]
            else:
                vals = _column_values(ds, c).astype(np.float64)
                qs = np.linspace(0, 1, self.bins + 1)[1:-1]
                self.edges[c] = np.quantile(vals, qs)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            out[c] = np.digitize(
                np.asarray(batch[c], dtype=np.float64), self.edges[c])
        return out


class Tokenizer(Preprocessor):
    """String column -> list-of-tokens column (reference
    `preprocessors/tokenizer.py`; default whitespace split)."""

    def __init__(self, columns: List[str],
                 tokenization_fn: Optional[Callable[[str], List[str]]] = None):
        self.columns = list(columns)
        self.fn = tokenization_fn or (lambda s: str(s).split())

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            toks = np.empty(len(np.atleast_1d(batch[c])), dtype=object)
            for i, s in enumerate(np.atleast_1d(batch[c])):
                toks[i] = self.fn(s)
            out[c] = toks
        return out


class CountVectorizer(Preprocessor):
    """Token counts over a fitted vocabulary, one count column per token
    (reference `preprocessors/vectorizer.py` CountVectorizer)."""

    def __init__(self, columns: List[str],
                 tokenization_fn: Optional[Callable[[str], List[str]]] = None,
                 max_features: Optional[int] = None):
        self.columns = list(columns)
        self.fn = tokenization_fn or (lambda s: str(s).split())
        self.max_features = max_features
        self.vocab: Dict[str, List[str]] = {}

    def _fit(self, ds: Datastream) -> None:
        from collections import Counter

        for c in self.columns:
            counts: Counter = Counter()
            for s in _column_values(ds, c):
                counts.update(self.fn(s))
            items = counts.most_common(self.max_features)
            self.vocab[c] = sorted(tok for tok, _ in items)

    def _transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            vocab = self.vocab[c]
            idx = {t: i for i, t in enumerate(vocab)}
            rows = np.atleast_1d(batch[c])
            mat = np.zeros((len(rows), len(vocab)), dtype=np.int64)
            for i, s in enumerate(rows):
                for tok in self.fn(s):
                    j = idx.get(tok)
                    if j is not None:
                        mat[i, j] += 1
            for j, tok in enumerate(vocab):
                out[f"{c}_{tok}"] = mat[:, j]
        return out


class FeatureHasher(Preprocessor):
    """Token -> fixed-width hashed count features, vocabulary-free
    (reference `preprocessors/hasher.py`)."""

    def __init__(self, columns: List[str], num_features: int,
                 output_column_name: str = "hashed_features"):
        self.columns = list(columns)
        self.num_features = num_features
        self.out = output_column_name

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        import zlib

        rows = len(np.atleast_1d(batch[self.columns[0]]))
        mat = np.zeros((rows, self.num_features), dtype=np.int64)
        for c in self.columns:
            for i, v in enumerate(np.atleast_1d(batch[c])):
                toks = v if isinstance(v, (list, np.ndarray)) else [v]
                for t in np.asarray(toks).tolist():
                    h = zlib.crc32(str(t).encode()) % self.num_features
                    mat[i, h] += 1
        out = {k: v for k, v in batch.items() if k not in self.columns}
        out[self.out] = mat
        return out
