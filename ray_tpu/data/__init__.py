from ray_tpu.data.datastream import (
    ActorPoolStrategy,
    Datastream,
    Dataset,
    DataIterator,
    GroupedData,
    from_items,
    from_numpy,
    range as range_,
    range_tensor,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_tfrecords,
    read_text,
    from_pandas,
    from_arrow,
)

from ray_tpu.data.expressions import ColumnPredicate, col

from ray_tpu.data.datasources import (
    read_images,
    read_mongo,
    read_sql,
    read_webdataset,
    write_webdataset,
)

# reference-compatible module-level names
range = range_  # noqa: A001 (shadows builtin deliberately, like ray.data.range)

from ray_tpu.data import preprocessors
