"""Distributed two-stage shuffle: map tasks partition, reduce tasks merge.

Mirrors the reference's push-based shuffle / sort design
(`python/ray/data/_internal/push_based_shuffle.py`,
`_internal/planner/exchange/sort_task_spec.py`): stage 1 runs one task per
input block that splits it into N output partitions (by range boundary for
sort, by hash for groupby, by seeded RNG for random_shuffle); stage 2 runs
one task per output partition that merges its pieces. All rows move through
the object store — the driver never materializes the dataset.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, List, Optional, Union

import numpy as np

import ray_tpu

KeyT = Union[str, Callable[[Any], Any]]


def _stable_hash(k) -> int:
    if isinstance(k, (int, np.integer)):
        return int(k) & 0x7FFFFFFF
    import zlib

    return zlib.crc32(repr(k).encode())


def _key_values(block, key: KeyT) -> np.ndarray:
    """Vector of sort/group keys for a block."""
    from ray_tpu.data.datastream import _block_rows

    if isinstance(block, dict) and isinstance(key, str):
        return np.asarray(block[key])
    rows = _block_rows(block)
    if callable(key):
        return np.asarray([key(r) for r in rows])
    return np.asarray([r[key] for r in rows])


def _take_rows(block, idx: np.ndarray):
    from ray_tpu.data.datastream import _block_rows, _rows_to_block

    if isinstance(block, dict):
        return {k: np.asarray(v)[idx] for k, v in block.items()}
    rows = _block_rows(block)
    return _rows_to_block([rows[i] for i in idx])


def _sample_boundaries(blocks: List, key: KeyT, n: int,
                       sample_per_block: int = 64) -> List[Any]:
    """Approximate range boundaries from per-block key samples."""
    samples: List[Any] = []
    for b in blocks:
        kv = _key_values(b, key)
        if len(kv) == 0:
            continue
        take = min(sample_per_block, len(kv))
        sel = np.linspace(0, len(kv) - 1, take).astype(int)
        samples.extend(kv[sel].tolist())
    if not samples:
        return []
    samples.sort()
    return [samples[int(len(samples) * (i + 1) / n)]
            for i in range(n - 1) if int(len(samples) * (i + 1) / n) < len(samples)]


def _partition_block(block_or_ref, ops, n: int, mode: str, key, boundaries,
                     seed: int):
    """Stage-1 map task: apply pending ops, split into n partitions."""
    from ray_tpu.data.datastream import _apply_ops, _block_len

    block = _apply_ops(block_or_ref, ops)
    m = _block_len(block)
    if m == 0:
        empty = _take_rows(block, np.array([], dtype=int))
        return tuple(empty for _ in range(n)) if n > 1 else empty
    if mode == "sort":
        kv = _key_values(block, key)
        assign = np.array([bisect.bisect_right(boundaries, k) for k in kv.tolist()])
    elif mode == "hash":
        kv = _key_values(block, key)
        # process-independent hash: map tasks run in different worker
        # processes, where Python's salted hash() would scatter equal keys
        assign = np.array([_stable_hash(k) % n for k in kv.tolist()])
    else:  # random
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, n, size=m)
    parts = tuple(_take_rows(block, np.nonzero(assign == p)[0])
                  for p in range(n))
    return parts if n > 1 else parts[0]


def _merge_partition(mode: str, key, seed: int, *pieces):
    """Stage-2 reduce task: merge this partition's pieces from every map."""
    from ray_tpu.data.datastream import _block_len, _concat_blocks

    merged = _concat_blocks(list(pieces))
    m = _block_len(merged)
    if m == 0:
        return merged
    if mode == "sort":
        kv = _key_values(merged, key)
        order = np.argsort(kv, kind="stable")
        return _take_rows(merged, order)
    if mode == "random":
        rng = np.random.default_rng(seed)
        return _take_rows(merged, rng.permutation(m))
    return merged  # hash: grouping only needs co-location


def shuffle_refs(block_refs: List, ops, *, mode: str, key: Optional[KeyT] = None,
                 num_partitions: Optional[int] = None,
                 seed: Optional[int] = None) -> List:
    """Run the two-stage exchange; returns the new block refs."""
    n_in = len(block_refs)
    n = num_partitions or max(1, n_in)
    boundaries: List[Any] = []
    if mode == "sort":
        # boundary sampling needs materialized key columns: run the pending
        # ops once on a sample of blocks (they re-run in stage 1; cheap
        # relative to the exchange, same trade the reference makes).
        probe = [_apply_remote.remote(r, ops) for r in block_refs[:8]]
        boundaries = _sample_boundaries(ray_tpu.get(probe), key, n)
        n = len(boundaries) + 1

    part = ray_tpu.remote(_partition_block).options(num_returns=n)
    # unseeded shuffles must differ between calls (per-epoch reshuffling)
    base_seed = seed if seed is not None else int(
        np.random.SeedSequence().entropy % (2 ** 31))
    partss = []
    for i, ref in enumerate(block_refs):
        out = part.remote(ref, ops, n, mode, key, boundaries, base_seed + i)
        partss.append([out] if n == 1 else out)

    merge = ray_tpu.remote(_merge_partition)
    return [merge.remote(mode, key, base_seed + 7919 * p,
                         *[parts[p] for parts in partss])
            for p in range(n)]


@ray_tpu.remote
def _apply_remote(block_or_ref, ops):
    from ray_tpu.data.datastream import _apply_ops

    return _apply_ops(block_or_ref, ops)
