"""Extended datasources: images, SQL, WebDataset, mongo.

Reference parity: python/ray/data/datasource/{image_datasource.py,
sql_datasource.py, webdataset_datasource.py, mongo_datasource.py}. Each
reader fans file/shard loading out as one task per input, like the rest of
ray_tpu.data (datastream.py read_* constructors).
"""

from __future__ import annotations

import builtins
import os
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.datastream import Block, Datastream, _block_rows, _rows_to_block

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff", ".webp")


def _expand_paths(paths: Union[str, List[str]], exts=None) -> List[str]:
    paths = [paths] if isinstance(paths, str) else list(paths)
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                full = os.path.join(p, name)
                if os.path.isfile(full) and (
                        exts is None or name.lower().endswith(exts)):
                    out.append(full)
        else:
            out.append(p)
    return out


def read_images(paths: Union[str, List[str]], *,
                size: Optional[tuple] = None,
                mode: Optional[str] = None,
                include_paths: bool = False) -> Datastream:
    """Decode image files into HWC uint8 arrays (column "image").

    `size=(h, w)` resizes; `mode` converts colorspace ("RGB", "L", ...).
    Mirrors reference ImageDatasource options.
    """
    files = _expand_paths(paths, _IMAGE_EXTS)

    @ray_tpu.remote
    def load(path: str) -> Block:
        from PIL import Image

        img = Image.open(path)
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        row: Dict[str, Any] = {"image": np.asarray(img)}
        if include_paths:
            row["path"] = path
        return [row]

    return Datastream([load.remote(p) for p in files])


def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             parallelism: int = 1,
             shard_column: Optional[str] = None) -> Datastream:
    """Run a SQL query through a DB-API connection factory.

    With `shard_column` + `parallelism>1`, issues one modular-hash-sharded
    query per task (the reference shards on an integer key the same way);
    otherwise a single task runs the query as-is.
    """
    def fetch(query: str) -> Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(query)
            cols = [d[0] for d in cur.description]
            return _rows_to_block(
                [dict(zip(cols, row)) for row in cur.fetchall()])
        finally:
            conn.close()

    remote_fetch = ray_tpu.remote(fetch)
    if shard_column and parallelism > 1:
        # normalize negatives ((x % n + n) % n) and NULLs (shard 0) so no
        # row can fall outside every shard
        c, n = shard_column, parallelism
        shard_expr = f"COALESCE((({c} % {n}) + {n}) % {n}, 0)"
        queries = [
            f"SELECT * FROM ({sql}) AS _rt_shard WHERE {shard_expr} = {i}"
            for i in builtins.range(parallelism)]
    else:
        queries = [sql]
    return Datastream([remote_fetch.remote(q) for q in queries])


def _decode_wds_member(name: str, data: bytes) -> Any:
    ext = name.rsplit(".", 1)[-1].lower()
    if ext in ("jpg", "jpeg", "png", "bmp", "gif", "webp"):
        import io

        from PIL import Image

        return np.asarray(Image.open(io.BytesIO(data)))
    if ext in ("json",):
        import json

        return json.loads(data)
    if ext in ("txt", "text", "cls", "cls2"):
        text = data.decode()
        return int(text) if ext.startswith("cls") else text
    if ext in ("npy",):
        import io

        return np.load(io.BytesIO(data))
    return data


def read_webdataset(paths: Union[str, List[str]], *,
                    decode: bool = True) -> Datastream:
    """WebDataset tar shards: members grouped by key prefix, one row per
    sample with a column per extension (reference webdataset_datasource.py).
    """
    shards = _expand_paths(paths, (".tar",))

    @ray_tpu.remote
    def load(path: str) -> Block:
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." not in base:
                    continue
                key, ext = base.split(".", 1)
                data = tf.extractfile(member).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = (
                    _decode_wds_member(base, data) if decode else data)
        return [samples[k] for k in order]

    return Datastream([load.remote(p) for p in shards])


def write_webdataset(ds: Datastream, path: str) -> List[str]:
    """Write one .tar shard per block. Arrays go as .npy, str as .txt,
    dict/list as .json, bytes raw."""
    import io
    import json
    import tarfile

    os.makedirs(path, exist_ok=True)

    def encode(value: Any) -> tuple:
        if isinstance(value, np.generic):
            value = value.item()
        if isinstance(value, bytes):
            return "bin", value
        if isinstance(value, str):
            return "txt", value.encode()
        if isinstance(value, np.ndarray):
            buf = io.BytesIO()
            np.save(buf, value)
            return "npy", buf.getvalue()
        return "json", json.dumps(value, default=str).encode()

    def write_block(block: Block, out_path: str) -> None:
        with tarfile.open(out_path, "w") as tf:
            for i, row in enumerate(_block_rows(block)):
                if not isinstance(row, dict):
                    row = {"data": row}
                key = str(row.get("__key__", i))
                for col, value in row.items():
                    if col == "__key__":
                        continue
                    ext, data = encode(value)
                    # the member's LAST extension must be the codec's, or
                    # read_webdataset would decode with the wrong one
                    name = (f"{key}.{col}" if col.endswith(f".{ext}")
                            else f"{key}.{col}.{ext}")
                    info = tarfile.TarInfo(name)
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))

    return ds._write(os.path.join(path, "shard"), "tar", write_block)


def read_mongo(uri: str, database: str, collection: str, *,
               query: Optional[dict] = None,
               parallelism: int = 1) -> Datastream:
    """MongoDB reader (gated: requires pymongo, absent in this image)."""
    try:
        import pymongo  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_mongo requires pymongo, which is not installed") from e

    @ray_tpu.remote
    def load(skip: int, limit: int) -> Block:
        import pymongo

        client = pymongo.MongoClient(uri)
        try:
            # sort by _id so skip/limit windows partition deterministically
            # across the parallel shard queries
            cursor = (client[database][collection]
                      .find(query or {}).sort("_id", 1)
                      .skip(skip).limit(limit))
            return _rows_to_block(
                [{k: v for k, v in doc.items() if k != "_id"}
                 for doc in cursor])
        finally:
            client.close()

    import pymongo

    client = pymongo.MongoClient(uri)
    try:
        total = client[database][collection].count_documents(query or {})
    finally:
        client.close()
    per = -(-total // parallelism) if total else 1
    return Datastream([load.remote(i, per)
                       for i in builtins.range(0, max(total, 1), per)])
