"""Core-primitive microbenchmarks (reference `python/ray/_private/ray_perf.py:93-282`,
run by `ray microbenchmark`): ops/s for tasks, actor calls, and object
put/get, plus submission-side metrics for the task-path fast lanes — p50
`.remote()` call latency and per-task TaskSpec wire bytes (which the
export-once function table drops from O(closure) to O(FunctionID)).

Requires an initialized runtime (`ray_tpu.init()` first or run via the CLI,
which boots one).

CLI:
  python -m ray_tpu.microbenchmark              # full suite, one JSON row/line
  python -m ray_tpu.microbenchmark --quick --json   # CI smoke: small batches,
                                                    # short timers, one JSON doc
"""

from __future__ import annotations

import pickle
import time
from typing import Callable, Dict, List

import numpy as np

import ray_tpu


def _rate(fn: Callable[[], int], min_seconds: float = 2.0) -> float:
    """ops/s: run batches of fn until min_seconds elapsed."""
    fn()  # warm up (worker spawn, compile)
    done = 0
    t0 = time.perf_counter()
    while True:
        done += fn()
        dt = time.perf_counter() - t0
        if dt >= min_seconds:
            return done / dt


@ray_tpu.remote
def _noop():
    return None


@ray_tpu.remote
def _noop_arg(x):
    return x


def _make_closure_fn(nbytes: int = 1024 * 1024):
    """A remote no-op capturing `nbytes` of state. Built INSIDE a function
    on purpose: a nested def is always cloudpickled BY VALUE, so the
    payload genuinely rides the export/spec — a module-level def would be
    pickled by reference whenever this module is imported (CLI subcommand,
    pytest) and the benchmark would measure a ~100-byte spec."""
    payload = b"c" * nbytes

    @ray_tpu.remote
    def _noop_closure():
        return len(payload)

    return _noop_closure


@ray_tpu.remote
class _BenchActor:
    def method(self):
        return None

    def echo(self, x):
        return x


def _submission_metrics(record, quick: bool) -> None:
    """Submission-side fast-lane metrics: p50 time of an individual
    `.remote()` call, and the pickled TaskSpec size for a closure-heavy
    function on its first vs steady-state submission."""
    from ray_tpu.core import api as _api

    n = 50 if quick else 300
    lat: List[float] = []
    refs = []
    ray_tpu.get(_noop.remote())  # ensure export + a warm worker
    for _ in range(n):
        t0 = time.perf_counter()
        refs.append(_noop.remote())
        lat.append(time.perf_counter() - t0)
    ray_tpu.get(refs)
    lat.sort()
    record("task_submit_p50", lat[len(lat) // 2] * 1e6, unit="us")

    w = _api._global_worker()
    if not hasattr(w, "_spec_bytes_probe"):
        return  # client mode: specs are built server-side
    payload = b"z" * (256 * 1024)

    @ray_tpu.remote
    def _closure_heavy():
        return len(payload)

    sizes: List[int] = []
    w._spec_bytes_probe = lambda spec: sizes.append(
        len(pickle.dumps(spec, protocol=5)))
    try:
        ray_tpu.get(_closure_heavy.remote())
        ray_tpu.get(_closure_heavy.remote())
    finally:
        w._spec_bytes_probe = None
    record("task_wire_bytes_first", sizes[0], unit="bytes")
    record("task_wire_bytes_steady", sizes[1], unit="bytes")


def _completion_metrics(record, quick: bool) -> None:
    """Return-path fast-lane metrics: p50 end-to-end latency of one task
    (submit -> result landed at the owner, the adaptive-flush idle path) and
    drain throughput of a deep queue of no-ops (the batched path: dominated
    by result delivery, task_done handling and scheduler wakeups, not by
    submission)."""
    ray_tpu.get(_noop.remote())  # warm worker + export
    n = 30 if quick else 100
    lat: List[float] = []
    for _ in range(n):
        t0 = time.perf_counter()
        ray_tpu.get(_noop.remote())
        lat.append(time.perf_counter() - t0)
    lat.sort()
    record("task_e2e_p50", lat[len(lat) // 2] * 1e6, unit="us")

    depth = 200 if quick else 2000
    t0 = time.perf_counter()
    ray_tpu.get([_noop.remote() for _ in range(depth)])
    record("task_completions_per_s", depth / (time.perf_counter() - t0))


def run_microbenchmark(batch: int = 100, quick: bool = False) -> List[Dict]:
    """`quick` = CI smoke mode: small batches and short timers so the whole
    suite runs in seconds on CPU while still driving every primitive."""
    min_seconds = 0.2 if quick else 2.0
    if quick:
        batch = min(batch, 25)
    results: List[Dict] = []

    def record(name: str, rate: float, unit: str = "ops/s"):
        results.append({"benchmark": name, "rate": round(rate, 1), "unit": unit})

    def rate(fn):
        return _rate(fn, min_seconds=min_seconds)

    # tasks: batched submit + get
    record("tasks_sync_batch", rate(
        lambda: len(ray_tpu.get([_noop.remote() for _ in range(batch)]))))

    # single task round-trip latency expressed as ops/s
    record("task_roundtrip", rate(
        lambda: (ray_tpu.get(_noop.remote()), 1)[1]))

    arg = b"y" * 1024
    record("tasks_1kb_arg_batch", rate(
        lambda: len(ray_tpu.get([_noop_arg.remote(arg) for _ in range(batch)]))))

    # the function-table acceptance benchmark: same 1 MiB-closure function
    # submitted N times (export-once -> specs carry a 16-byte id)
    closure_fn = _make_closure_fn()
    record("tasks_1mb_closure_batch", rate(
        lambda: len(ray_tpu.get([closure_fn.remote() for _ in range(batch)]))))

    a = _BenchActor.options(num_cpus=0).remote()
    record("actor_calls_sync_batch", rate(
        lambda: len(ray_tpu.get([a.method.remote() for _ in range(batch)]))))
    record("actor_call_roundtrip", rate(
        lambda: (ray_tpu.get(a.method.remote()), 1)[1]))
    record("actor_echo_1kb_batch", rate(
        lambda: len(ray_tpu.get([a.echo.remote(arg) for _ in range(batch)]))))

    small = b"x" * 1024
    record("put_1kb", rate(
        lambda: ([ray_tpu.put(small) for _ in range(batch)], batch)[1]))

    _object_plane_metrics(record, rate, batch, quick)

    _submission_metrics(record, quick)
    _completion_metrics(record, quick)

    ray_tpu.kill(a)
    return results


def _object_plane_metrics(record, rate, batch: int, quick: bool) -> None:
    """Data-plane rows (zero-copy object plane, ROADMAP item 3). Row names
    are scale-independent — the zero-copy path made the full sizes cheap
    enough for the quick/CI profile, so the regression floors always
    compare like with like."""
    # same-node put+get of a 10 MB numpy array: put is one obj_create
    # round-trip + one aligned write into a (usually recycled) segment;
    # get attaches the segment and deserializes in place
    big = np.zeros(10 * 1024 * 1024 // 8)
    def put_get_big():
        ref = ray_tpu.put(big)
        out = ray_tpu.get(ref)
        return int(out.nbytes)
    record("put_get_10mb_bytes", rate(put_get_big), unit="bytes/s")

    # 100 MB numpy roundtrip: the zero-copy headline — the returned array
    # is a read-only view into shared memory, so the cycle cost is ONE
    # aligned write plus control overhead
    huge = np.zeros(100 * 1024 * 1024 // 8)
    def np_roundtrip():
        out = ray_tpu.get(ray_tpu.put(huge))
        assert not out.flags.writeable  # views, not copies
        return int(out.nbytes)
    record("np_roundtrip_100mb", rate(np_roundtrip), unit="bytes/s")
    del huge

    # 32 MB raw-bytes roundtrip: serve payloads / rollout blobs are plain
    # `bytes`, not numpy — the serializer's out-of-band blob lane (PR 16)
    # must put them on the same zero-copy plane (in-band pickle costs two
    # extra full-memory passes per cycle: one into the pickle stream, one
    # into the frame)
    blob = b"\x00" * (32 * 1024 * 1024)
    def bytes_roundtrip():
        out = ray_tpu.get(ray_tpu.put(blob))
        assert len(out) == len(blob)
        return len(blob)
    record("put_get_32mb_raw_bytes", rate(bytes_roundtrip), unit="bytes/s")
    del blob

    # 1 MB arg fanned out to a batch of tasks through ONE shared ref: every
    # executor materializes the arg (and its 1 MB echo) through the
    # object plane — tasks/s, the RLAX rollout-traffic shape
    @ray_tpu.remote
    def _echo_arg(x):
        return x
    arg = np.zeros(1 << 20, dtype=np.uint8)
    arg_ref = ray_tpu.put(arg)
    fan = max(4, batch // 4) if quick else batch
    record("arg_1mb_fanout", rate(
        lambda: len(ray_tpu.get([_echo_arg.remote(arg_ref)
                                 for _ in range(fan)]))))


def run_objplane(quick: bool = False):
    """The object-plane acceptance benchmark (OBJPLANE artifact): just the
    data-plane rows, at full sizes, on an initialized runtime."""
    results: List[Dict] = []

    def record(name: str, rate_v: float, unit: str = "ops/s"):
        results.append({"benchmark": name, "rate": round(rate_v, 1),
                        "unit": unit})

    def rate(fn):
        return _rate(fn, min_seconds=0.5 if quick else 2.0)

    ray_tpu.get(_noop.remote())  # warm worker + export
    _object_plane_metrics(record, rate, batch=100, quick=quick)
    return results


def main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="ray_tpu.microbenchmark")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one JSON document instead of a row per line")
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke mode: small batches, short timers")
    parser.add_argument("--batch", type=int, default=100)
    parser.add_argument("--objplane", metavar="PATH", default=None,
                        help="run ONLY the object-plane rows (full sizes) "
                             "and write the OBJPLANE artifact JSON here")
    args = parser.parse_args(argv)

    own_cluster = not ray_tpu.is_initialized()
    if own_cluster:
        ray_tpu.init(num_cpus=4)
    try:
        if args.objplane:
            from ray_tpu.envelope import _hardware

            rows = run_objplane(quick=args.quick)
            art = {
                "bench": "object-plane (zero-copy pin protocol)",
                "quick": args.quick,
                "hardware": _hardware(),
                "baseline": {"artifact": "ENVELOPE_r10.json",
                             "put_get_10mb_bytes": 1307360966.1},
                "results": rows,
            }
            rate = {r["benchmark"]: r["rate"] for r in rows}
            art["put_get_10mb_speedup"] = round(
                rate["put_get_10mb_bytes"]
                / art["baseline"]["put_get_10mb_bytes"], 2)
            text = json.dumps(art, indent=2)
            with open(args.objplane, "w") as f:
                f.write(text + "\n")
            print(text)
            return 0
        rows = run_microbenchmark(batch=args.batch, quick=args.quick)
        if args.as_json:
            print(json.dumps({"quick": args.quick, "batch": args.batch,
                              "results": rows}))
        else:
            for row in rows:
                print(json.dumps(row))
    finally:
        if own_cluster:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
