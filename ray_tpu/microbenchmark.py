"""Core-primitive microbenchmarks (reference `python/ray/_private/ray_perf.py:93-282`,
run by `ray microbenchmark`): ops/s for tasks, actor calls, and object
put/get. Requires an initialized runtime (`ray_tpu.init()` first or run via
the CLI, which boots one).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np

import ray_tpu


def _rate(fn: Callable[[], int], min_seconds: float = 2.0) -> float:
    """ops/s: run batches of fn until min_seconds elapsed."""
    fn()  # warm up (worker spawn, compile)
    done = 0
    t0 = time.perf_counter()
    while True:
        done += fn()
        dt = time.perf_counter() - t0
        if dt >= min_seconds:
            return done / dt


@ray_tpu.remote
def _noop():
    return None


@ray_tpu.remote
def _noop_arg(x):
    return x


@ray_tpu.remote
class _BenchActor:
    def method(self):
        return None

    def echo(self, x):
        return x


def run_microbenchmark(batch: int = 100) -> List[Dict]:
    results: List[Dict] = []

    def record(name: str, rate: float, unit: str = "ops/s"):
        results.append({"benchmark": name, "rate": round(rate, 1), "unit": unit})

    # tasks: batched submit + get
    record("tasks_sync_batch", _rate(
        lambda: len(ray_tpu.get([_noop.remote() for _ in range(batch)]))))

    # single task round-trip latency expressed as ops/s
    record("task_roundtrip", _rate(
        lambda: (ray_tpu.get(_noop.remote()), 1)[1]))

    arg = b"y" * 1024
    record("tasks_1kb_arg_batch", _rate(
        lambda: len(ray_tpu.get([_noop_arg.remote(arg) for _ in range(batch)]))))

    a = _BenchActor.options(num_cpus=0).remote()
    record("actor_calls_sync_batch", _rate(
        lambda: len(ray_tpu.get([a.method.remote() for _ in range(batch)]))))
    record("actor_call_roundtrip", _rate(
        lambda: (ray_tpu.get(a.method.remote()), 1)[1]))
    record("actor_echo_1kb_batch", _rate(
        lambda: len(ray_tpu.get([a.echo.remote(arg) for _ in range(batch)]))))

    small = b"x" * 1024
    record("put_1kb", _rate(
        lambda: ([ray_tpu.put(small) for _ in range(batch)], batch)[1]))

    big = np.zeros(10 * 1024 * 1024 // 8)  # 10 MB
    def put_get_big():
        ref = ray_tpu.put(big)
        out = ray_tpu.get(ref)
        return int(out.nbytes)
    record("put_get_10mb_bytes", _rate(put_get_big), unit="bytes/s")

    ray_tpu.kill(a)
    return results


def main() -> int:
    import json

    own_cluster = not ray_tpu.is_initialized()
    if own_cluster:
        ray_tpu.init(num_cpus=4)
    try:
        for row in run_microbenchmark():
            print(json.dumps(row))
    finally:
        if own_cluster:
            ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
