"""ray_tpu: a TPU-native distributed compute framework.

A brand-new framework with the capability surface of Ray (tasks, actors,
objects, placement groups, Data/Train/Tune/Serve/RL) designed TPU-first:
the compute path is JAX/XLA/Pallas over `jax.sharding.Mesh`es, collectives
are compiler-emitted over ICI/DCN rather than NCCL library calls, and the
scheduler treats ICI-connected TPU slices as first-class topology-aware
resources.

Public core API (mirrors the reference's `ray` module surface,
/root/reference/python/ray/_private/worker.py:1115,2391,2538,2600,2929):

    import ray_tpu as ray
    ray.init()
    @ray.remote
    def f(x): return x + 1
    ref = f.remote(1)
    ray.get(ref)
"""

from ray_tpu._version import __version__

# Core public API (lazy-bound to avoid importing jax at `import ray_tpu` time).
from ray_tpu.core.api import (
    get_gpu_ids,
    get_tpu_ids,
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    push,
    wait,
    kill,
    cancel,
    get_actor,
    method,
    nodes,
    cluster_resources,
    available_resources,
    get_runtime_context,
    timeline,
)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.exceptions import (
    RayTpuError,
    TaskError,
    ActorError,
    ActorDiedError,
    OutOfMemoryError,
    WorkerCrashedError,
    ObjectLostError,
    GetTimeoutError,
    PlacementInfeasibleError,
    RequestTimeoutError,
    BackPressureError,
)

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "method",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "push",
    "ActorClass",
    "ActorHandle",
    "RayTpuError",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "OutOfMemoryError",
    "WorkerCrashedError",
    "ObjectLostError",
    "GetTimeoutError",
    "PlacementInfeasibleError",
    "RequestTimeoutError",
    "BackPressureError",
]

__all__.append("util")


def __getattr__(name):
    # `ray_tpu.util` attribute access like the reference's `ray.util`,
    # loaded lazily (PEP 562) so bare `import ray_tpu` stays light.
    if name == "util":
        import importlib

        return importlib.import_module("ray_tpu.util")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
