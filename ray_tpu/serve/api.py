"""Serve: model serving on actors.

Mirrors the reference's anatomy (SURVEY §3.5): a detached ServeController
actor (`python/ray/serve/controller.py:73`) reconciles per-deployment target
replica counts into replica actors (`_private/deployment_state.py:1009`);
handles route requests with power-of-two-choices over client-tracked
in-flight counts (`_private/router.py:263,224`); replicas report queue
lengths and a queue-based policy autoscales within [min,max]
(`_private/autoscaling_policy.py:127`); config updates reach handles via
versioned long-polls (`_private/long_poll.py`). The HTTP ingress is a
proxy actor running a stdlib threading HTTP server (the reference uses
uvicorn/Starlette — an external dep this build avoids).

TPU twist: a deployment may set `resources={"TPU": n}` so replicas pin to
chips/slices; model weights travel to replicas through the object store.
"""

from __future__ import annotations

import heapq
import json
import logging
import random
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.core import rpc as _rpc
from ray_tpu.core.exceptions import (ActorDiedError, BackPressureError,
                                     ObjectLostError, RequestTimeoutError,
                                     WorkerCrashedError)
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_serve_controller"
SERVE_VERSIONS_CHANNEL = "serve_replica_versions"
PROXY_NAME = "_serve_http_proxy"
GRPC_PROXY_NAME = "_serve_grpc_proxy"

# The named fault-injection point at the router->replica call boundary
# (rpc.fault_point): chaos rules like `sever:serve_replica_call:0.02`
# sever/drop/delay individual replica submissions without touching the
# rest of the worker's links, driving the failover path deterministically.
REPLICA_CALL_FAULT_POINT = "serve_replica_call"


def _serve_cfg():
    """Serve runtime knobs; imported lazily (serve.config imports this
    module for the declarative-deploy half)."""
    from ray_tpu.serve.config import get_serve_config

    return get_serve_config()


# Process-local router outcome counters (storm harness + tests read these
# without a metrics scrape; the tagged metrics below feed dashboards).
_router_stats_lock = threading.Lock()
_router_stats: Dict[str, int] = {
    "retries": 0, "failovers": 0, "shed": 0, "timeouts": 0}


def _bump_router_stat(key: str, n: int = 1) -> None:
    with _router_stats_lock:
        _router_stats[key] = _router_stats.get(key, 0) + n


def router_stats() -> Dict[str, int]:
    """Snapshot of this process's router outcome counters: `retries`
    (re-routed attempts), `failovers` (requests that succeeded only after
    a retry), `shed` (admission-control rejections), `timeouts` (promises
    failed by the deadline reaper), `client_cancels` (in-flight replica
    attempts cancelled because the client disconnected)."""
    with _router_stats_lock:
        return dict(_router_stats)


def reset_router_stats() -> None:
    with _router_stats_lock:
        for k in _router_stats:
            _router_stats[k] = 0


_router_pool_lock = threading.Lock()
_router_pool_inst = None


def _router_pool():
    """Small shared executor for router work that must not run on the RPC
    reader thread: failover resubmissions (socket sends + backoff sleeps)
    and plasma-sized result relays (a blocking pull)."""
    global _router_pool_inst
    with _router_pool_lock:
        if _router_pool_inst is None:
            from concurrent.futures import ThreadPoolExecutor

            _router_pool_inst = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="serve-router")
        return _router_pool_inst


class _DeadlineReaper:
    """Shared wall-clock timer for the router. Two entry kinds: `watch`
    entries resolve still-pending router promises with a typed
    RequestTimeoutError at their deadline — the guarantee that no serve
    request outlives its deadline even when every other signal (replica
    death notice, result push) is lost — and `schedule` entries run a
    (cheap) callable at a time, which failover uses for its backoff waits
    so no router-pool thread ever sleeps. One heap + one lazy thread per
    process."""

    def __init__(self):
        self._cv = threading.Condition()
        self._heap: List[tuple] = []
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def watch(self, deadline_ts: float, promise, name: str,
              timeout_s: float) -> None:
        # store the bare ObjectID, NOT the ObjectRef: holding the ref would
        # pin every promise (and its inline result blob) in the worker's
        # object table for the full timeout after the request completed —
        # memory scaling with rps x timeout x response size. With only the
        # id, a completed-and-dropped promise is freed normally and the
        # expire entry finds nothing to do.
        self._push(deadline_ts, ("expire", promise.id, name, timeout_s))

    def schedule(self, when_ts: float, fn: Callable[[], None]) -> None:
        """Run `fn` at wall-clock `when_ts` on the timer thread — `fn`
        must be cheap/non-blocking (hand real work to the router pool)."""
        self._push(when_ts, ("call", fn))

    def _push(self, ts: float, entry: tuple) -> None:
        with self._cv:
            self._seq += 1
            # the unique seq means heapq never compares the entry payload
            heapq.heappush(self._heap, (ts, self._seq, entry))
            t = self._thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._loop,
                                     name="serve-deadline-reaper", daemon=True)
                self._thread = t
                t.start()
            self._cv.notify_all()

    def _loop(self) -> None:
        from ray_tpu.core.api import _global_worker

        while True:
            with self._cv:
                if not self._heap:
                    self._cv.wait(timeout=1.0)
                    if not self._heap:
                        # exit decision under the cv (watch() holds it while
                        # pushing + checking liveness) so no entry strands
                        self._thread = None
                        return
                due = self._heap[0][0]
                wait = due - time.time()
                if wait > 0:
                    self._cv.wait(timeout=min(wait, 1.0))
                    continue
                _, _, entry = heapq.heappop(self._heap)
            try:
                if entry[0] == "call":
                    entry[1]()
                    continue
                _, oid, name, timeout_s = entry
                from ray_tpu.core.object_ref import ObjectRef

                # ad-hoc ref (never _counted): carries the id for the
                # table lookup without touching the distributed refcount
                promise = ObjectRef(oid)
                w = _global_worker()
                state, _ = w.peek_local(promise)
                timed_out = state == "pending" and w.fulfill_promise(
                    promise, error=RequestTimeoutError(
                        f"request to {name} exceeded its "
                        f"{timeout_s:.1f}s deadline"))
                if timed_out:
                    _bump_router_stat("timeouts")
                    _serve_metrics()["timeouts"].inc(
                        tags={"deployment": name})
                # registry cleanup ALWAYS happens here (bounded lifetime:
                # one expire entry per request); on a real timeout also
                # CANCEL the in-flight replica attempt through the
                # runtime's task cancellation — nobody will read the
                # result, so the replica should stop computing it
                with _inflight_lock:
                    req = _inflight_requests.pop(oid, None)
                if timed_out and req is not None \
                        and req.current_ref is not None:
                    try:
                        w.cancel(req.current_ref)
                    except Exception:
                        logger.debug("post-deadline replica cancel failed",
                                     exc_info=True)
            except Exception:
                logger.exception("deadline reaper entry failed")


_deadline_reaper = _DeadlineReaper()

# promise.id -> live _RouterRequest: lets the deadline reaper and the HTTP
# edge's disconnect path CANCEL the replica attempt behind an abandoned
# request (rides the runtime's real task cancellation). Entries are popped
# at fulfillment, at cancel, or — worst case — by the request's own
# deadline-reaper expire entry, so the registry lifetime is bounded by the
# request timeout.
_inflight_requests: Dict[bytes, "_RouterRequest"] = {}
_inflight_lock = threading.Lock()


def cancel_inflight(promise_ref) -> bool:
    """Best-effort cancellation of the replica attempt behind a router
    promise (client disconnected / caller abandoned the request): the
    in-flight `handle_request` task is cancelled through `ray_tpu.cancel`
    — cooperative interruption on the replica — and the promise resolves
    to the typed TaskCancelledError so any residual waiter unblocks.
    Returns False when the request already completed."""
    from ray_tpu.core.api import _global_worker
    from ray_tpu.core.exceptions import TaskCancelledError

    with _inflight_lock:
        req = _inflight_requests.pop(promise_ref.id, None)
    if req is None:
        return False
    w = _global_worker()
    cancelled = w.fulfill_promise(
        req.promise, error=TaskCancelledError(
            "serve request cancelled (client disconnected)"))
    if req.current_ref is not None:
        try:
            w.cancel(req.current_ref)
        except Exception:
            logger.debug("inflight replica cancel failed", exc_info=True)
    if cancelled:
        _bump_router_stat("client_cancels")
    return cancelled

# errors that mean "this replica (or the link to it) died mid-request" —
# the request itself is intact and an idempotent one may be re-routed
# (ConnectionError covers rpc.RpcDisconnected, e.g. a severed submission)
_RETRYABLE_ERRORS = (ActorDiedError, WorkerCrashedError, ObjectLostError,
                     ConnectionError)


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 5.0


def _cfg_eq(a, b) -> bool:
    """Structural equality robust to ndarray-bearing configs (== on those
    raises) and to handle-bearing init args: compare pickled bytes, treat
    any serialization asymmetry as 'changed' (the safe direction — it
    falls back to a full rolling update)."""
    if a is b:
        return True
    try:
        return cloudpickle.dumps(a) == cloudpickle.dumps(b)
    except Exception:  # arbitrary user objects: any pickling error = not equal
        return False


def _replica_key(r) -> bytes:
    """Stable identity for a replica handle: the ACTOR id, not id(handle) —
    handle objects are recreated (and their id() reused by the allocator),
    and controller-local maps die with the controller."""
    aid = getattr(r, "_actor_id", None) or getattr(r, "actor_id", None)
    return aid.binary() if hasattr(aid, "binary") else bytes(str(aid), "utf8")


@ray_tpu.remote
class _ReplicaActor:
    def __init__(self, def_blob: bytes, init_args, init_kwargs,
                 def_version: int = 0, user_config: Any = None):
        target = cloudpickle.loads(def_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **(init_kwargs or {}))
        else:
            self._callable = target
        if user_config is not None:
            self.reconfigure(user_config)
        self._inflight = 0
        # The deployment-definition version this replica was built from
        # lives ON the replica: a restarted controller recovers it by
        # asking, instead of defaulting every pre-restart replica to
        # "current" and silently skipping their rollout (reference keeps
        # the version in DeploymentReplica state, deployment_state.py).
        self._def_version = def_version
        # Replica lifecycle hook: deployments that run background machinery
        # (e.g. LLMDeployment's engine driver thread) start it here, once
        # the instance is fully constructed/reconfigured. A raising hook
        # fails replica construction — the controller retries elsewhere.
        start = getattr(self._callable, "__serve_start__", None)
        if callable(start):
            start()

    def def_version(self) -> int:
        return self._def_version

    def prepare_stop(self) -> bool:
        """Graceful-stop lifecycle hook (`__serve_stop__`), invoked
        best-effort by the controller before a kill. Hard kills (crashes,
        chaos) skip it — hooks must not be load-bearing for correctness."""
        stop = getattr(self._callable, "__serve_stop__", None)
        if callable(stop):
            stop()
        return True

    def reconfigure(self, user_config) -> bool:
        """Apply a new user_config in place (reference replica
        reconfigure): class deployments implement reconfigure(cfg)."""
        fn = getattr(self._callable, "reconfigure", None)
        if fn is None:
            raise ValueError(
                "deployment got user_config but defines no reconfigure()")
        fn(user_config)
        return True

    def handle_request(self, method_name: str, args, kwargs,
                       deadline_ts: Optional[float] = None):
        # Remaining-time check BEFORE dispatch: a request whose end-to-end
        # deadline expired while queued on this replica is dropped with the
        # typed error instead of occupying an execution slot — under
        # overload the slots go to requests that can still meet their
        # deadline (reference request_timeout_s semantics).
        if deadline_ts is not None and time.time() >= deadline_ts:
            raise RequestTimeoutError(
                f"request expired in replica queue (deadline exceeded by "
                f"{time.time() - deadline_ts:.3f}s before dispatch)")
        from ray_tpu.serve import batching as _batching

        self._inflight += 1
        prev = _batching.push_request_deadline(deadline_ts)
        try:
            # function deployments and class __call__ both route through the
            # callable itself; named methods are looked up on the instance
            fn = (self._callable if method_name == "__call__"
                  else getattr(self._callable, method_name))
            return fn(*args, **(kwargs or {}))
        finally:
            _batching.pop_request_deadline(prev)
            self._inflight -= 1

    def health(self) -> bool:
        return True


@ray_tpu.remote
class ServeController:
    """Reconciles deployment target state into replica actors."""

    _KV_KEY = "controller_state"

    def __init__(self):
        self._deployments: Dict[str, dict] = {}
        self._replicas: Dict[str, List[Any]] = {}
        self._replica_def_version: Dict[bytes, int] = {}  # actor id -> def ver
        self._version_queries: Dict[bytes, Any] = {}  # in-flight def_version asks
        self._versions: Dict[str, int] = {}
        self._version_cv = threading.Condition()
        self._probes: Dict[str, dict] = {}  # deployment -> {replica: ref}
        self._shutdown = False
        import uuid

        # distinguishes controller incarnations: a handle comparing versions
        # across a controller restart (or a torn-down-and-rebooted cluster)
        # must not mistake a coincidentally-equal version for "no change"
        self._incarnation = uuid.uuid4().hex
        self._restoring = True
        try:
            self._restore_state()
        finally:
            self._restoring = False
        self._thread = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._thread.start()

    # -------------------------------------------------------- fault tolerance
    def _checkpoint(self) -> None:
        """Persist deployment specs + live replica actor ids to the GCS KV
        (reference serve checkpoints its state the same way,
        serve/_private/storage/kv_store.py): a crashed controller's
        replacement re-adopts running replicas instead of orphaning them."""
        state = {
            "deployments": {
                name: {k: d[k] for k in (
                    "def_blob", "init_args", "init_kwargs", "target",
                    "actor_options", "autoscaling", "max_concurrency",
                    "def_version", "app_ingress", "user_config") if k in d}
                for name, d in self._deployments.items()},
            "replicas": {name: [r.actor_id for r in rs]
                         for name, rs in self._replicas.items()},
        }
        try:
            from ray_tpu.core.api import _global_worker

            _global_worker().gcs.call("kv_put", {
                "namespace": "serve", "key": self._KV_KEY,
                "value": cloudpickle.dumps(state)}, timeout=5)
        except Exception:
            logger.debug("serve controller checkpoint failed", exc_info=True)

    def _restore_state(self) -> None:
        """Fresh controller: re-adopt the previous incarnation's deployments
        and still-alive replicas from the KV checkpoint. Replica definition
        versions are NOT in the checkpoint — they are recovered from the
        replicas themselves (_replica_version), so a redeploy right after a
        controller crash still rolls pre-crash replicas."""
        from ray_tpu.core.actor import ActorHandle
        from ray_tpu.core.api import _global_worker

        from ray_tpu.util.backoff import ExponentialBackoff

        # Retry the checkpoint read across a control-plane outage: a
        # controller restarting DURING a head replacement would otherwise
        # cold-start and silently orphan every running replica. Bounded —
        # a checkpoint that truly doesn't exist still cold-starts fast.
        backoff = ExponentialBackoff(base_s=0.2, cap_s=2.0)
        blob = None
        for attempt in range(4):
            try:
                blob = _global_worker().gcs.call(
                    "kv_get", {"namespace": "serve", "key": self._KV_KEY},
                    timeout=5)
                break
            except (OSError, RuntimeError, TimeoutError):  # GCS unreachable
                if attempt == 3:
                    logger.warning(
                        "serve controller checkpoint unreadable (GCS down?); "
                        "cold-starting without re-adoption")
                    return
                backoff.sleep()
        if not blob:
            return
        try:
            state = cloudpickle.loads(blob)
        except Exception:
            logger.exception("corrupt serve controller checkpoint; ignoring")
            return
        for name, d in state.get("deployments", {}).items():
            self._deployments[name] = {
                **d, "last_scale_up": 0.0, "last_scale_down": 0.0,
                "_draining": []}
        for name, aids in state.get("replicas", {}).items():
            live = []
            for aid in aids:
                try:
                    info = _global_worker().get_actor_info(actor_id=aid)
                    if info and info.get("state") == "ALIVE":
                        live.append(ActorHandle(aid, "_ReplicaActor"))
                except (OSError, RuntimeError, TimeoutError, KeyError,
                        ValueError) as e:
                    logger.debug("replica %s liveness probe failed: %s",
                                 aid, e)
            if live:
                self._replicas[name] = live
        for name in self._deployments:
            self._bump_version(name)
        if self._deployments:
            logger.info("serve controller restored %d deployment(s), "
                        "re-adopted %d replica(s) from checkpoint",
                        len(self._deployments),
                        sum(len(v) for v in self._replicas.values()))

    def _bump_version(self, name: str) -> None:
        with self._version_cv:
            v = self._versions[name] = self._versions.get(name, 0) + 1
            self._version_cv.notify_all()
        # version bumps mark every deployment/replica-set change: checkpoint
        # here so the KV state trails live state by at most one change
        if not getattr(self, "_restoring", False):
            self._checkpoint()
        # Push the bump to handles over GCS pubsub: handles fetch the new
        # replica set with a NON-blocking get_replicas, so no controller
        # exec thread is ever parked on a handle's long-poll (reference
        # LongPollHost is async for the same reason, long_poll.py:186).
        try:
            from ray_tpu.core.api import _global_worker

            _global_worker().publish(SERVE_VERSIONS_CHANNEL,
                                     {"name": name, "version": v})
        except (OSError, RuntimeError):
            logger.debug("version push for %s lost", name, exc_info=True)
            # handles fall back to their periodic poll

    # -------------------------------------------------------------- deploy
    def deploy(self, name: str, def_blob: bytes, init_args, init_kwargs,
               num_replicas: int, actor_options: Optional[dict],
               autoscaling: Optional[AutoscalingConfig], max_concurrency: int,
               app_ingress: bool = False, user_config: Any = None):
        existing = self._deployments.get(name)
        if (existing is not None
                and not _cfg_eq(existing.get("user_config"), user_config)
                and existing["def_blob"] == def_blob
                and _cfg_eq(existing["init_args"], init_args)
                and _cfg_eq(existing["init_kwargs"], init_kwargs)
                and _cfg_eq(existing["actor_options"],
                            dict(actor_options or {}))
                and _cfg_eq(existing["autoscaling"], autoscaling)
                and existing["max_concurrency"] == max_concurrency
                and existing.get("app_ingress", False) == bool(app_ingress)):
            # user_config-only redeploy: push reconfigure() into live
            # replicas in place — no version bump, no rolling restart
            # (reference lightweight-update path, deployment_state.py).
            # The in-flight rolling candidate (if any) must get the new
            # config too — it may be promoted to serving next.
            targets = list(self._replicas.get(name, []))
            if existing.get("_rolling") is not None:
                targets.append(existing["_rolling"][0])
            try:
                ray_tpu.get([r.reconfigure.remote(user_config)
                             for r in targets], timeout=30)
            except Exception as e:
                # a replica rejected the config (no reconfigure(), or it
                # raised): fall through to a ROLLING redeploy so state
                # and reality re-converge instead of silently diverging
                logger.warning(
                    "in-place reconfigure of %s failed (%s); falling back "
                    "to rolling update", name, e)
            else:
                existing["user_config"] = user_config
                existing["target"] = (num_replicas if autoscaling is None
                                      else autoscaling.min_replicas)
                self._reconcile_one(name)
                return True
        # Redeploy = ROLLING update (reference DeploymentState version
        # rollout): old replicas keep serving; the reconcile loop replaces
        # them one at a time with health-checked new-definition replicas.
        def_version = (existing.get("def_version", 0) + 1) if existing else 0
        carried_draining = []
        if existing is not None:
            # a redeploy mid-rollout must not orphan the in-flight replica
            # (not serving yet — safe to kill) or the draining ones
            if existing.get("_rolling") is not None:
                self._kill_replica(name, existing["_rolling"][0])
            carried_draining = existing.get("_draining", [])
        self._deployments[name] = {
            "def_blob": def_blob,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "target": num_replicas if autoscaling is None else autoscaling.min_replicas,
            "actor_options": dict(actor_options or {}),
            "autoscaling": autoscaling,
            "max_concurrency": max_concurrency,
            "app_ingress": bool(app_ingress),
            "user_config": user_config,
            "last_scale_up": 0.0,
            "last_scale_down": 0.0,
            "def_version": def_version,
            "_draining": carried_draining,
        }
        self._reconcile_one(name)
        return True

    def reconfigure_deployment(self, name: str, user_config: Any) -> bool:
        """Lightweight update: push a new user_config into every live
        replica (and the in-flight rolling candidate) IN PLACE — no def_blob
        re-ship, no version bump, no rolling restart.  This is the weight
        broadcast path the RL fleet rides: the learner publishes
        {weights, epoch} here and each replica's reconfigure() applies (or
        epoch-fences) it.  Unlike the deploy() fallback, a replica failure
        here does NOT trigger a rolling redeploy — the caller owns retry
        policy — but the accepted config is recorded so reconcile hands it
        to any replacement replicas it starts later.
        """
        existing = self._deployments.get(name)
        if existing is None:
            raise KeyError(f"no deployment named {name!r}")
        targets = list(self._replicas.get(name, []))
        if existing.get("_rolling") is not None:
            targets.append(existing["_rolling"][0])
        # Record first: a replica that dies mid-push gets replaced by the
        # reconcile loop, and the replacement must init with the NEW config
        # (otherwise a crash window could resurrect fenced-out weights).
        existing["user_config"] = user_config
        self._checkpoint()
        errors = 0
        for r in targets:
            try:
                ray_tpu.get(r.reconfigure.remote(user_config), timeout=30)
            except Exception:
                errors += 1
                logger.warning("reconfigure push to a %s replica lost "
                               "(replica will pick config up on replace)",
                               name, exc_info=True)
        return errors == 0

    def delete_deployment(self, name: str):
        d = self._deployments.pop(name, None)
        self._probes.pop(name, None)
        doomed = list(self._replicas.pop(name, []))
        if d is not None:
            doomed += [r for r, _dl in d.get("_draining", [])]
            if d.get("_rolling") is not None:
                doomed.append(d["_rolling"][0])
        for r in doomed:
            self._kill_replica(name, r)
        self._bump_version(name)
        return d is not None

    def shutdown(self):
        self._shutdown = True
        for name in list(self._deployments):
            self.delete_deployment(name)
        try:
            from ray_tpu.core.api import _global_worker

            _global_worker().gcs.call("kv_del", {
                "namespace": "serve", "key": self._KV_KEY}, timeout=5)
        except (OSError, TimeoutError) as e:
            logger.debug("serve KV cleanup lost: %s", e)
        return True

    # ----------------------------------------------------------- discovery
    def get_replicas(self, name: str, known_version: int = -1,
                     timeout_s: float = 2.0):
        """Versioned long-poll (reference LongPollHost, long_poll.py:186):
        event-driven — the wait wakes on the version bump, not a poll."""
        deadline = time.monotonic() + timeout_s
        with self._version_cv:
            while self._versions.get(name, 0) == known_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._version_cv.wait(timeout=remaining)
        return {
            "version": self._versions.get(name, 0),
            "incarnation": self._incarnation,
            "replicas": list(self._replicas.get(name, [])),
            "app_ingress": bool(
                self._deployments.get(name, {}).get("app_ingress", False)),
        }

    def list_deployments(self):
        return {
            name: {"target": d["target"],
                   "replicas": len(self._replicas.get(name, []))}
            for name, d in self._deployments.items()
        }

    def metrics_snapshot(self):
        """Per-deployment queue depth (last autoscale poll) + replica
        counts, for the driver's Prometheus export."""
        return {
            name: {"replicas": len(self._replicas.get(name, [])),
                   "queue_depth": d.get("last_queue_depth", 0)}
            for name, d in self._deployments.items()
        }

    # ----------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        last_health = 0.0
        while not self._shutdown:
            time.sleep(0.25)
            try:
                now = time.monotonic()
                probe = now - last_health >= 1.0
                if probe:
                    last_health = now
                for name in list(self._deployments):
                    if probe:
                        self._health_check(name)
                    self._autoscale(name)
                    self._reconcile_one(name)
            except Exception:
                logger.exception("reconcile failed")

    def _health_check(self, name: str):
        """Drop replicas whose health probe ERRORS (actor process gone);
        the reconcile pass right after replaces them (reference
        DeploymentState check_and_update_replicas). Probes are
        asynchronous — a busy replica (probe queued behind requests) never
        blocks the reconcile loop and never counts as dead."""
        replicas = self._replicas.get(name, [])
        if not replicas:
            self._probes.pop(name, None)
            return
        probes = self._probes.setdefault(name, {})
        for r in replicas:
            if r not in probes:
                probes[r] = r.health.remote()
        dead = []
        for r in list(probes):
            if r not in replicas:  # replica already scaled away
                probes.pop(r)
                continue
            ready, _ = ray_tpu.wait([probes[r]], num_returns=1, timeout=0)
            if not ready:
                continue  # still queued/running — busy is not dead
            ref = probes.pop(r)
            try:
                ray_tpu.get(ref)
            except Exception:
                logger.warning("replica of %s failed health check; "
                               "replacing", name)
                dead.append(r)
                self._kill_replica(name, r)
        if dead:
            self._replicas[name] = [r for r in replicas if r not in dead]
            self._bump_version(name)

    def _blob_arg(self, d: dict):
        """Large deployment definitions (model weights baked into the
        class) ship as ONE plasma object with an owner-directed push
        broadcast (`ray_tpu.push`, reference push_manager.h:29): every
        replica node reads a local copy instead of each replica re-shipping
        the blob from the controller. Small definitions stay by-value."""
        blob = d["def_blob"]
        if len(blob) < (1 << 20):
            return blob
        ref = d.get("_def_blob_ref")
        if ref is None:
            ref = ray_tpu.put(blob)
            try:
                ray_tpu.push(ref)
            except Exception:
                logger.debug("def-blob push skipped", exc_info=True)
            d["_def_blob_ref"] = ref
        return ref

    def _new_replica(self, d: dict):
        opts = dict(d["actor_options"])
        opts["max_concurrency"] = max(d["max_concurrency"], 4)
        ver = d.get("def_version", 0)
        replica = _ReplicaActor.options(**opts).remote(
            self._blob_arg(d), d["init_args"], d["init_kwargs"],
            def_version=ver, user_config=d.get("user_config"))
        self._replica_def_version[_replica_key(replica)] = ver
        return replica

    def _replica_version(self, r) -> Optional[int]:
        """Definition version of a replica; None while unknown. Unknown
        versions (controller restarted: the map is empty) are recovered
        asynchronously from the replica itself so a redeploy after a
        controller restart still rolls pre-restart replicas."""
        key = _replica_key(r)
        v = self._replica_def_version.get(key)
        if v is not None:
            return v
        probe = self._version_queries.get(key)
        if probe is None:
            try:
                probe = r.def_version.remote()
            except Exception:
                return None
            self._version_queries[key] = probe
        done, _ = ray_tpu.wait([probe], num_returns=1, timeout=0)
        if not done:
            return None
        self._version_queries.pop(key, None)
        try:
            v = int(ray_tpu.get(probe, timeout=1))
        except Exception:
            return None  # health check handles dead replicas
        self._replica_def_version[key] = v
        return v

    def _kill_replica(self, name: str, r) -> None:
        self._replica_def_version.pop(_replica_key(r), None)
        self._version_queries.pop(_replica_key(r), None)
        self._evict_stats_client(r)
        try:
            # fire-and-forget graceful-stop hook; never waited on (a dead
            # replica would stall the reconcile loop)
            r.prepare_stop.remote()
        except Exception:
            pass
        try:
            ray_tpu.kill(r)
        except (OSError, RuntimeError, ValueError, KeyError):
            pass  # replica already dead — the goal state

    def _reconcile_one(self, name: str):
        d = self._deployments.get(name)
        if d is None:
            return
        replicas = self._replicas.setdefault(name, [])
        changed = False
        while len(replicas) < d["target"]:
            replicas.append(self._new_replica(d))
            changed = True
        while len(replicas) > d["target"]:
            # Downscale DRAINS like a rolling update: the displaced replica
            # leaves the routable set now (handles stop picking it on the
            # version bump) but keeps serving its in-flight requests until
            # idle, hard-killed only past the same drain_deadline_s knob.
            r = replicas.pop()
            d.setdefault("_draining", []).append(
                (r, time.monotonic() + _serve_cfg().drain_deadline_s))
            changed = True
        if self._advance_rollout(name, d, replicas):
            changed = True
        if changed:
            self._bump_version(name)

    def _advance_rollout(self, name: str, d: dict, replicas: List[Any]) -> bool:
        """One rolling-update step per reconcile pass (reference
        DeploymentState rollout): start a new-definition replica, wait for
        its health probe, then swap it in for ONE stale replica — the old
        version keeps serving throughout, and the displaced replica drains
        (kill once idle, or after the configurable drain_deadline_s)."""
        ver = d.get("def_version", 0)
        # reap draining replicas that are idle (or past deadline)
        draining = d.setdefault("_draining", [])
        still = []
        for r, deadline in draining:
            idle = False
            try:
                idle = self._worker_stats(r).get("load", 0) == 0
            except Exception:
                # transient stats failure must NOT count as idle (it would
                # kill a busy replica mid-request); the deadline bounds us
                idle = False
            if idle or time.monotonic() > deadline:
                self._kill_replica(name, r)
            else:
                still.append((r, deadline))
        d["_draining"] = still

        stale = [r for r in replicas
                 if self._replica_version(r) not in (None, ver)]
        roll = d.get("_rolling")
        if roll is None:
            if stale and len(replicas) >= d["target"]:
                nr = self._new_replica(d)
                d["_rolling"] = (nr, nr.health.remote())
            return False
        nr, probe = roll
        done, _ = ray_tpu.wait([probe], num_returns=1, timeout=0)
        if not done:
            return False
        ok = False
        try:
            ok = bool(ray_tpu.get(probe, timeout=1))
        except Exception:
            ok = False
        d["_rolling"] = None
        if not ok:
            self._kill_replica(name, nr)  # failed rollout step; retried next pass
            return False
        victim = next((r for r in replicas
                       if self._replica_version(r) not in (None, ver)), None)
        if victim is None:
            # the stale replica disappeared meanwhile (health-check kill +
            # refill at the current version): the set is already current,
            # and appending would overshoot target — next pass would kill
            # the fresh replica mid-request
            self._kill_replica(name, nr)
            return False
        replicas.append(nr)
        replicas.remove(victim)
        d["_draining"].append(
            (victim, time.monotonic() + _serve_cfg().drain_deadline_s))
        return True

    def _evict_stats_client(self, replica) -> None:
        cache = getattr(self, "_stats_clients", None)
        if not cache:
            return
        client = cache.pop(replica.actor_id, None)
        if client is not None:
            try:
                client.close()
            except OSError:
                pass  # socket already dropped

    def _worker_stats(self, replica) -> dict:
        """actor_stats RPC to the worker hosting `replica` (address cached;
        invalidated on connection errors so replaced replicas re-resolve)."""
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.core.api import _global_worker

        cache = getattr(self, "_stats_clients", None)
        if cache is None:
            cache = self._stats_clients = {}
        key = replica.actor_id
        client = cache.get(key)
        if client is None:
            info = _global_worker().get_actor_info(actor_id=key)
            if not info or not info.get("address"):
                raise RuntimeError("no address for replica")
            client = _rpc.connect_with_retry(info["address"], timeout=3)
            cache[key] = client
        try:
            return client.call("actor_stats", timeout=3)
        except Exception:
            self._evict_stats_client(replica)
            raise

    def _autoscale(self, name: str):
        """Queue-length-driven scaling (reference autoscaling_policy.py:127)."""
        d = self._deployments.get(name)
        if d is None or d["autoscaling"] is None:
            return
        cfg: AutoscalingConfig = d["autoscaling"]
        replicas = self._replicas.get(name, [])
        if not replicas:
            return
        # out-of-band load probe against each replica's WORKER (answered
        # from its RPC thread): an actor-method probe would queue behind
        # the very requests being measured and always read a drained queue
        qlens = []
        for r in replicas:
            try:
                stats = self._worker_stats(r)
                # `load` excludes our own health probes (they queue on the
                # same worker and would inflate every sample by 1)
                qlens.append(stats.get(
                    "load", stats["executing"] + stats["queued"]))
            except Exception:
                # partial stats must not drive scaling: a wrongly-low total
                # would trigger a scale-down of an overloaded deployment
                return
        total = sum(qlens)
        d["last_queue_depth"] = total
        desired = max(
            cfg.min_replicas,
            min(cfg.max_replicas,
                int(-(-total // max(cfg.target_num_ongoing_requests_per_replica, 1e-9)))
                or cfg.min_replicas))
        now = time.monotonic()
        if desired > d["target"] and now - d["last_scale_up"] > cfg.upscale_delay_s:
            d["target"] = desired
            d["last_scale_up"] = now
        elif desired < d["target"] and now - d["last_scale_down"] > cfg.downscale_delay_s:
            d["target"] = d["target"] - 1
            d["last_scale_down"] = now


# ------------------------------------------------------------------ handle


class DeploymentHandle:
    """Routes calls to replicas: power-of-two-choices over client-side
    in-flight counts (reference router.py:263). Thread-free data plane: the
    in-flight decrement is a completion callback on the ownership layer
    (no per-request thread), and replica-set updates arrive via ONE
    background long-poll loop per handle (reference LongPollClient,
    long_poll.py:68) instead of per-request controller polls."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._name = deployment_name
        self._method = method_name
        self._version = -1
        self._incarnation = None  # controller incarnation the version is from
        self._stream = False
        self._timeout_s: Optional[float] = None  # None -> config default
        self._idempotent = True  # False disables mid-request failover
        self._replicas: List[Any] = []
        # keyed by replica actor id, NOT list index: a replica-set change
        # must not let stale completions decrement a new replica's count
        self._inflight: Dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._refresher: Optional[threading.Thread] = None
        self._bumped = threading.Event()  # set by the pubsub push
        self._sub_cb = None
        self._closed = False

    def _controller(self):
        return ray_tpu.get_actor(CONTROLLER_NAME)

    _rkey = staticmethod(_replica_key)

    def _apply(self, info: dict) -> None:
        with self._lock:
            inc = info.get("incarnation")
            if inc != getattr(self, "_incarnation", None):
                self._incarnation = inc
                self._version = -1  # new controller: any version is news
            self._app_ingress = info.get("app_ingress", False)
            if info["version"] != self._version:
                self._version = info["version"]
                self._replicas = info["replicas"]
                # keep counts for surviving replicas; drop departed ones
                live = {self._rkey(r) for r in self._replicas}
                self._inflight = {k: v for k, v in self._inflight.items()
                                  if k in live}

    def _refresh(self, block: bool = True):
        # Cold start only (a handle with no replica set yet): a bounded 2s
        # server-side long-poll per round, NOT a busy poll — steady-state
        # refresh is push-driven and non-blocking (_ensure_refresher).
        deadline = time.monotonic() + 30
        while True:
            info = ray_tpu.get(self._controller().get_replicas.remote(
                self._name, self._version, 0.0 if not block else 2.0))
            self._apply(info)
            with self._lock:
                if self._replicas or not block or time.monotonic() > deadline:
                    return

    def _ensure_refresher(self) -> None:
        """Replica-set updates are PUSH-driven: the controller publishes
        version bumps over GCS pubsub and this loop answers each with a
        non-blocking get_replicas — no controller exec thread is parked per
        handle (any number of handles costs the controller one fan-out
        publish). A slow periodic poll backstops lost pushes. Both the loop
        and the pubsub callback hold the handle WEAKLY, so a dropped handle
        is collectable: its loop exits and its subscription self-removes."""
        import weakref

        with self._lock:
            t = self._refresher
            if t is not None and t.is_alive():
                return

            wself = weakref.ref(self)

            def on_bump(msg):
                s = wself()
                if s is None:  # handle was GC'd: self-unsubscribe
                    try:
                        from ray_tpu.core.api import _global_worker

                        _global_worker().unsubscribe_channel(
                            SERVE_VERSIONS_CHANNEL, on_bump)
                    except (OSError, KeyError, ValueError):
                        pass  # worker shutting down; channel dies with it
                    return
                if msg.get("name") == s._name:
                    s._bumped.set()

            def loop():
                # Subscribe from the refresher thread, never the request
                # path: a stalled GCS must not wedge remote() calls (and
                # the handle lock is not held here).
                s = wself()
                if s is None:
                    return
                if s._sub_cb is None:
                    try:
                        from ray_tpu.core.api import _global_worker

                        _global_worker().subscribe_channel(
                            SERVE_VERSIONS_CHANNEL, on_bump)
                        s._sub_cb = on_bump
                    except Exception:
                        pass  # poll-only fallback
                # plain Event/str locals do not pin the handle
                bumped, name = s._bumped, s._name
                del s
                failures = 0
                while failures < 5:
                    bumped.wait(timeout=5.0)
                    bumped.clear()
                    s = wself()
                    if s is None or s._closed:
                        return
                    try:
                        info = ray_tpu.get(s._controller().get_replicas.remote(
                            name, s._version, 0.0), timeout=30)
                        s._apply(info)
                        failures = 0
                    except Exception:
                        # Controller gone (serve.shutdown) or unreachable:
                        # exit after a few strikes rather than spinning
                        # forever; the next remote() restarts the loop.
                        failures += 1
                    del s  # don't pin the handle across the wait
                    if failures:
                        time.sleep(1.0)
                s = wself()
                if s is not None:
                    with s._lock:
                        if s._refresher is threading.current_thread():
                            s._refresher = None

            t = threading.Thread(target=loop,
                                 name=f"serve-refresh-{self._name}",
                                 daemon=True)
            self._refresher = t
            t.start()

    def close(self) -> None:
        self._closed = True
        self._bumped.set()
        if self._sub_cb is not None:
            try:
                from ray_tpu.core.api import _global_worker

                _global_worker().unsubscribe_channel(
                    SERVE_VERSIONS_CHANNEL, self._sub_cb)
            except (OSError, KeyError, ValueError):
                pass  # worker shutting down; channel dies with it
            self._sub_cb = None

    def options(self, method_name: str = "__call__", stream: bool = False,
                timeout_s: Optional[float] = None,
                idempotent: bool = True) -> "DeploymentHandle":
        h = DeploymentHandle(self._name, method_name)
        h._stream = stream
        h._timeout_s = timeout_s
        h._idempotent = idempotent
        return h

    # ------------------------------------------------------------- routing
    def _inc(self, key: bytes) -> None:
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1

    def _dec(self, key: bytes) -> None:
        with self._lock:
            self._inflight[key] = max(0, self._inflight.get(key, 1) - 1)

    def _pick_replica(self, exclude=()):
        """Power-of-two-choices among replicas that are under the
        configured in-flight cap and not in `exclude` (replicas already
        tried by this request's failover). Returns (replica, key) or
        (None, None) when no replica is eligible — the admission-control
        shed signal when `exclude` is empty."""
        cap = _serve_cfg().max_queue_per_replica
        with self._lock:
            candidates = []
            for r in self._replicas:
                k = self._rkey(r)
                if k in exclude:
                    continue
                if self._inflight.get(k, 0) < cap:
                    candidates.append((r, k))
            if not candidates:
                return None, None
            if len(candidates) == 1:
                return candidates[0]
            a, b = random.sample(range(len(candidates)), 2)
            pick = (a if self._inflight.get(candidates[a][1], 0)
                    <= self._inflight.get(candidates[b][1], 0) else b)
            return candidates[pick]

    def _resolve_deadline(self, timeout_s: Optional[float],
                          deadline_ts: Optional[float]):
        """(deadline_ts, timeout_s): explicit deadline wins (an ingress
        already started the request's clock at parse time), else per-call
        timeout, else the handle default, else the config default. Wall
        clock, so the deadline survives the hop into the replica process."""
        if deadline_ts is not None:
            return deadline_ts, max(0.0, deadline_ts - time.time())
        t = timeout_s if timeout_s is not None else self._timeout_s
        if t is None:
            t = _serve_cfg().request_timeout_s
        return time.time() + t, t

    def remote(self, *args, _timeout_s: Optional[float] = None,
               _deadline_ts: Optional[float] = None, **kwargs):
        _serve_metrics()["requests"].inc(tags={"deployment": self._name})
        # Route span: joins the caller's trace (e.g. the proxy's ingress
        # span) or roots a fresh one. Its span id rides the request as
        # trace_ctx so EVERY replica attempt — including failover retries —
        # parents under this one routing decision.
        route_ctx = None
        t_route = 0.0
        if tracing.enabled():
            amb = tracing.current_ctx()
            route_ctx = (amb[0] if amb else tracing.new_id(),
                         tracing.new_id())
            t_route = tracing.now_us()
        deadline_ts, timeout_s = self._resolve_deadline(
            _timeout_s, _deadline_ts)
        with self._lock:
            have = bool(self._replicas)
        if not have:
            self._refresh()
            with self._lock:
                if not self._replicas:
                    raise RuntimeError(
                        f"deployment {self._name} has no replicas")
        self._ensure_refresher()
        if getattr(self, "_stream", False):
            return self._submit_stream(args, kwargs, deadline_ts)

        replica, key = self._pick_replica()
        if replica is None:
            self._shed()
        budget = (_serve_cfg().request_retry_budget
                  if self._idempotent else 0)
        req = _RouterRequest(self, args, kwargs, deadline_ts, timeout_s,
                             budget)
        req.trace_ctx = route_ctx
        try:
            req._submit_to(replica, key)
        except Exception as e:
            # a submit-time severed link is the same failure class as a
            # mid-request death: route it through the failover budget
            if isinstance(e, _RETRYABLE_ERRORS) and req.retries_left > 0:
                req.tried.add(key)
                _router_pool().submit(req._failover, e)
            else:
                # resolve the already-watched promise so the reaper
                # doesn't later count a spurious timeout for an error
                # the caller received synchronously
                from ray_tpu.core.api import _global_worker

                _global_worker().fulfill_promise(req.promise, error=e)
                req._deregister()
                raise
        if route_ctx is not None:
            amb = tracing.current_ctx()
            tracing.add_complete(
                f"route::{self._name}", "serve_route",
                t_route, tracing.now_us() - t_route,
                trace_id=route_ctx[0], span_id=route_ctx[1],
                parent_id=amb[1] if amb else "",
                deployment=self._name)
        return req.promise

    def _submit_stream(self, args, kwargs, deadline_ts: float):
        """Streaming call (reference handle.options(stream=True)): the
        replica method returns a generator; items arrive as a dynamic-
        return stream consumable while the replica still runs. Failover
        covers the SUBMIT boundary only — once items may have been
        produced, a replay could duplicate them, so a mid-stream death
        surfaces as the typed ActorDiedError instead (promptly: the
        ownership layer fails the stream when the actor dies)."""
        from ray_tpu.core.api import _global_worker

        budget = _serve_cfg().request_retry_budget if self._idempotent else 0
        tried: set = set()
        last_err: Optional[Exception] = None
        route_ctx = None
        t_route = 0.0
        if tracing.enabled():
            amb = tracing.current_ctx()
            route_ctx = (amb[0] if amb else tracing.new_id(),
                         tracing.new_id())
            t_route = tracing.now_us()
        for attempt in range(budget + 1):
            replica, key = self._pick_replica(tried)
            if replica is None:
                if last_err is not None:
                    raise last_err
                self._shed()
            self._inc(key)
            try:
                _rpc.fault_point(REPLICA_CALL_FAULT_POINT)
                with tracing.ctx_scope(route_ctx):
                    gen = replica.handle_request.options(
                        num_returns="dynamic").remote(
                            self._method, args, kwargs, deadline_ts)
            except Exception as e:
                self._dec(key)
                if isinstance(e, _RETRYABLE_ERRORS) and attempt < budget:
                    tried.add(key)
                    last_err = e
                    _bump_router_stat("retries")
                    continue
                raise
            _global_worker().add_done_callback(
                gen._gen_ref, lambda k=key: self._dec(k))
            if route_ctx is not None:
                amb = tracing.current_ctx()
                tracing.add_complete(
                    f"route::{self._name}", "serve_route",
                    t_route, tracing.now_us() - t_route,
                    trace_id=route_ctx[0], span_id=route_ctx[1],
                    parent_id=amb[1] if amb else "",
                    deployment=self._name, stream=True)
            return gen
        raise last_err  # budget spent

    def _shed(self):
        _bump_router_stat("shed")
        _serve_metrics()["shed"].inc(tags={"deployment": self._name})
        cfg = _serve_cfg()
        with self._lock:
            n = len(self._replicas)
        raise BackPressureError(
            f"deployment {self._name} shed request: all {n} replicas at "
            f"the in-flight cap ({cfg.max_queue_per_replica})")

    def __reduce__(self):
        # routing options must survive serialization: a handle passed into
        # another deployment keeps its stream/timeout/idempotence behavior
        return (_rebuild_handle,
                (self._name, self._method, getattr(self, "_stream", False),
                 self._timeout_s, self._idempotent))


def _rebuild_handle(name: str, method: str, stream: bool,
                    timeout_s: Optional[float] = None,
                    idempotent: bool = True) -> "DeploymentHandle":
    h = DeploymentHandle(name, method)
    h._stream = stream
    h._timeout_s = timeout_s
    h._idempotent = idempotent
    return h


class _RouterRequest:
    """One routed unary request. Owns the caller-visible PROMISE ref
    (worker.create_promise) and chases replica attempts until success, a
    non-retryable error, a spent retry budget, or the deadline — so a
    replica dying mid-request re-routes the work without changing the ref
    the caller (or the HTTP edge's completion callback) is holding.
    Completion callbacks run on the RPC reader thread and only relay
    blobs; anything that sleeps or touches sockets (failover resubmits,
    plasma-sized result pulls) hops to the shared router pool."""

    __slots__ = ("h", "args", "kwargs", "deadline_ts", "retries_left",
                 "tried", "promise", "backoff", "retried", "trace_ctx",
                 "current_ref")

    def __init__(self, h: DeploymentHandle, args, kwargs,
                 deadline_ts: float, timeout_s: float, budget: int):
        from ray_tpu.core.api import _global_worker
        from ray_tpu.util.backoff import ExponentialBackoff

        cfg = _serve_cfg()
        self.h = h
        self.args = args
        self.kwargs = kwargs
        self.deadline_ts = deadline_ts
        self.retries_left = budget
        self.tried: set = set()
        self.retried = False
        self.backoff = ExponentialBackoff(
            base_s=cfg.retry_backoff_base_ms / 1000.0,
            cap_s=cfg.retry_backoff_cap_ms / 1000.0)
        self.promise = _global_worker().create_promise()
        self.trace_ctx = None  # (trace_id, route span id) when tracing is on
        self.current_ref = None  # latest replica attempt (cancellation target)
        with _inflight_lock:
            _inflight_requests[self.promise.id] = self
        _deadline_reaper.watch(deadline_ts, self.promise, h._name, timeout_s)

    def _deregister(self) -> None:
        """Request resolved: drop it from the cancellation registry (the
        reaper's expire entry remains the backstop cleanup)."""
        with _inflight_lock:
            _inflight_requests.pop(self.promise.id, None)

    def _submit_to(self, replica, key: bytes) -> None:
        h = self.h
        h._inc(key)
        try:
            _rpc.fault_point(REPLICA_CALL_FAULT_POINT)
            # every attempt (first submit AND pool-thread failovers) submits
            # under the route span's context, so retries stay in-trace
            with tracing.ctx_scope(self.trace_ctx):
                ref = replica.handle_request.remote(
                    h._method, self.args, self.kwargs, self.deadline_ts)
        except BaseException:
            h._dec(key)
            raise
        self.current_ref = ref  # cancellation target for disconnect/expiry
        from ray_tpu.core.api import _global_worker

        _global_worker().add_done_callback(
            ref, lambda: self._on_done(ref, key))

    def _on_done(self, ref, key: bytes) -> None:
        """Attempt completed (runs on the RPC reader thread: cheap,
        non-blocking — classify and relay, or hand off to the pool)."""
        from ray_tpu.core import serialization
        from ray_tpu.core.api import _global_worker

        h = self.h
        h._dec(key)
        w = _global_worker()
        state, blob = w.peek_local(ref)
        if state == "inline":
            # count the failover only if this result actually WON the
            # promise — a success landing after the deadline reaper already
            # timed the request out must not count as both
            if (w.fulfill_promise_blob(self.promise, blob, is_error=False)
                    and self.retried):
                _bump_router_stat("failovers")
            self._deregister()
            return
        if state == "plasma":
            _router_pool().submit(self._relay_plasma, ref)
            return
        if state != "error":
            logger.warning("router attempt for %s resolved in unexpected "
                           "state %r", h._name, state)
            return
        try:
            err = serialization.loads(blob)
        except Exception as e:
            err = e
        if (isinstance(err, _RETRYABLE_ERRORS) and self.retries_left > 0
                and time.time() < self.deadline_ts):
            self.tried.add(key)
            _router_pool().submit(self._failover, err)
            return
        w.fulfill_promise_blob(self.promise, blob, is_error=True)
        self._deregister()

    def _relay_plasma(self, ref) -> None:
        """Pool: pull a plasma-sized result and resolve the promise.
        Costs one deserialize+reserialize (the promise stores the value
        inline under its own id — the store copy lives under the ATTEMPT's
        id, which the caller never sees); true zero-copy would need object
        aliasing in the store. Serve results are overwhelmingly small, so
        this path is rare; revisit if large-result serving appears."""
        from ray_tpu.core.api import _global_worker

        try:
            value = ray_tpu.get(
                ref, timeout=max(1.0, self.deadline_ts - time.time() + 5.0))
        except Exception as e:
            _global_worker().fulfill_promise(self.promise, error=e)
            self._deregister()
            return
        if (_global_worker().fulfill_promise(self.promise, value=value)
                and self.retried):
            _bump_router_stat("failovers")
        self._deregister()

    def _failover(self, err: BaseException, ready: bool = False) -> None:
        """Pool: budget/deadline-bounded re-route onto a surviving replica.
        The full-jitter backoff wait (util/backoff.py) is SCHEDULED on the
        shared timer, never slept in the pool — a mass replica kill with
        many requests in flight must not park every pool thread in sleeps
        and starve plasma relays. The root-cause error is preserved across
        no-eligible-replica scans (each still charges the budget, so the
        loop stays bounded even before the deadline)."""
        from ray_tpu.core.api import _global_worker

        h = self.h
        if time.time() >= self.deadline_ts:
            return  # the deadline reaper resolves the promise (typed)
        if self.retries_left <= 0:
            _global_worker().fulfill_promise(self.promise, error=err)
            self._deregister()
            return
        if not ready:
            remaining = self.deadline_ts - time.time()
            delay = min(self.backoff.next_delay(), max(0.0, remaining))
            _deadline_reaper.schedule(
                time.time() + delay,
                lambda: _router_pool().submit(self._failover, err, True))
            return
        self.retries_left -= 1
        try:
            # the controller may already have replaced the dead replica:
            # pick up the freshest set without parking on a long-poll
            h._refresh(block=False)
        except Exception:
            pass  # stale set still usable; push refresh is the backstop
        replica, key = h._pick_replica(self.tried)
        if replica is None:
            # keep the root-cause error: the controller may replace the
            # dead replica before the next scan, and if the budget runs
            # out the caller should see what actually failed
            self._failover(err)
            return
        self.retried = True
        _bump_router_stat("retries")
        _serve_metrics()["retries"].inc(tags={"deployment": h._name})
        try:
            self._submit_to(replica, key)
        except Exception as e:
            self.tried.add(key)
            self._failover(e)


# ------------------------------------------------------------------ public


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    max_concurrent_queries: int = 8
    init_args: tuple = ()
    init_kwargs: Optional[dict] = None
    # pushed to replicas via their reconfigure() method; changing ONLY
    # this on redeploy updates live replicas in place, no restart
    # (reference deployment user_config / Deployment.reconfigure)
    user_config: Optional[Any] = None

    def bind(self, *args, **kwargs) -> "Deployment":
        import dataclasses as dc

        return dc.replace(self, init_args=args, init_kwargs=kwargs)

    def options(self, **opts) -> "Deployment":
        import dataclasses as dc

        return dc.replace(self, **opts)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None,
               max_concurrent_queries: int = 8,
               user_config: Optional[Any] = None):
    """`@serve.deployment` (reference python/ray/serve/api.py:261)."""

    def wrap(target):
        auto = None
        if autoscaling_config:
            auto = AutoscalingConfig(**autoscaling_config) \
                if isinstance(autoscaling_config, dict) else autoscaling_config
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=auto,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
        )

    return wrap(_func_or_class) if _func_or_class is not None else wrap


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return ServeController.options(
            # handles are push-driven (pubsub bump -> non-blocking
            # get_replicas), so concurrency only needs to cover bursts of
            # deploy/status/refresh calls, not a parked poll per handle
            name=CONTROLLER_NAME, num_cpus=0, max_concurrency=64).remote()


def _collect_graph(root: Deployment, order: List[Deployment],
                   seen: set, visiting: set) -> None:
    """Topo-sort the deployment DAG reachable through bound init args
    (reference deployment-graph build, _private/deployment_graph_build.py)."""
    if id(root) in visiting:
        raise ValueError(f"deployment graph has a cycle at {root.name!r}")
    if id(root) in seen:
        return
    visiting.add(id(root))
    for a in list(root.init_args) + list((root.init_kwargs or {}).values()):
        if isinstance(a, Deployment):
            _collect_graph(a, order, seen, visiting)
    visiting.discard(id(root))
    seen.add(id(root))
    order.append(root)


_handle_cache: Dict[tuple, DeploymentHandle] = {}
_handle_cache_lock = threading.Lock()


def _cached_handle(name: str, method: str = "__call__",
                   stream: bool = False) -> DeploymentHandle:
    """One long-lived handle per (deployment, method, stream) in this
    process: repeated lookups reuse the replica set, in-flight accounting,
    and the single pubsub refresher instead of growing a handle per call."""
    from ray_tpu.core.api import _global_worker

    try:
        world = _global_worker().address
    except Exception:
        world = None
    with _handle_cache_lock:
        h = _handle_cache.get((name, method, stream))
        # a cached handle from a torn-down-and-rebooted cluster (its worker
        # address differs) holds dead replicas — replace it
        if h is None or h._closed or getattr(h, "_world", None) != world:
            h = DeploymentHandle(name, method)
            h._stream = stream
            h._world = world
            _handle_cache[(name, method, stream)] = h
        return h


def _close_cached_handles() -> None:
    with _handle_cache_lock:
        handles = list(_handle_cache.values())
        _handle_cache.clear()
    for h in handles:
        h.close()


def _resolve_arg(a):
    return DeploymentHandle(a.name) if isinstance(a, Deployment) else a


def run(target: Deployment, *, name: str = "default") -> DeploymentHandle:
    """Deploy (a graph of) deployments and return the root handle
    (reference serve.run, api.py:460). Bound init args that are themselves
    deployments deploy first and arrive as DeploymentHandles — the
    composition model of the reference's deployment graphs."""
    controller = _get_or_create_controller()
    order: List[Deployment] = []
    _collect_graph(target, order, set(), set())
    names = [d.name for d in order]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate deployment names in graph: {names}")
    for d in order:
        init_args = tuple(_resolve_arg(a) for a in d.init_args)
        init_kwargs = {k: _resolve_arg(v)
                       for k, v in (d.init_kwargs or {}).items()} or None
        ray_tpu.get(controller.deploy.remote(
            d.name,
            cloudpickle.dumps(d.func_or_class),
            init_args,
            init_kwargs,
            d.num_replicas,
            d.ray_actor_options,
            d.autoscaling_config,
            d.max_concurrent_queries,
            getattr(d.func_or_class, "_serve_app_ingress", False),
            d.user_config,
        ))
    handle = _cached_handle(target.name)
    handle._refresh()
    return handle


def _serve_metrics() -> Dict[str, Any]:
    """Per-process serve metric instances (lazily registered so importing
    serve doesn't pollute the registry of processes that never serve)."""
    from ray_tpu.util.metrics import get_or_create

    return {
        "requests": get_or_create(
            "counter", "ray_tpu_serve_requests_total", "handle calls",
            tag_keys=("deployment",)),
        "errors": get_or_create(
            "counter", "ray_tpu_serve_errors_total", "failed requests",
            tag_keys=("deployment",)),
        "shed": get_or_create(
            "counter", "ray_tpu_serve_shed_total",
            "requests rejected by admission control",
            tag_keys=("deployment",)),
        "retries": get_or_create(
            "counter", "ray_tpu_serve_retries_total",
            "failover re-routes after replica loss",
            tag_keys=("deployment",)),
        "timeouts": get_or_create(
            "counter", "ray_tpu_serve_timeouts_total",
            "requests failed at their end-to-end deadline",
            tag_keys=("deployment",)),
        "latency": get_or_create(
            "histogram", "ray_tpu_serve_latency_seconds", "request latency",
            boundaries=(0.005, 0.02, 0.1, 0.5, 2, 10),
            tag_keys=("deployment",)),
        "queue_depth": get_or_create(
            "gauge", "ray_tpu_serve_queue_depth",
            "total replica queue depth", tag_keys=("deployment",)),
        "replicas": get_or_create(
            "gauge", "ray_tpu_serve_replicas", "running replicas",
            tag_keys=("deployment",)),
    }


def _update_serve_gauges() -> None:
    """Pull serve series from the processes that own them (called by the
    dashboard on /metrics scrape): request/error/latency live in the HTTP
    proxy actor, queue depth + replica counts in the controller."""
    from ray_tpu.util import metrics as metrics_mod

    # The single driver-started proxy plus every per-node proxy
    # (PROXY_NAME:<hex8>): each merges under its own source so counters sum.
    proxy_names = [PROXY_NAME]
    try:
        from ray_tpu import state as _state

        # unnamed actors list name=None — the .get default only covers a
        # MISSING key (this hid as an AttributeError under a broad except
        # until r04, silently dropping every per-node proxy from scrapes)
        proxy_names += [a["name"] for a in _state.list_actors()
                        if (a.get("name") or "").startswith(PROXY_NAME + ":")
                        and a.get("state") == "ALIVE"]
    except (OSError, RuntimeError, TimeoutError, KeyError, ValueError) as e:
        # RuntimeError covers RpcCallError: scrapes can race teardown, and
        # per-node proxies are optional — the driver proxy still collects
        logger.debug("proxy discovery via state API failed: %s", e)
    for name in proxy_names:
        try:
            proxy = ray_tpu.get_actor(name)
            metrics_mod.merge_snapshot(
                ray_tpu.get(proxy.metrics_snapshot.remote(), timeout=5),
                source=name)
        except Exception:
            pass  # ingress not running (handle-only traffic counts locally)
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    snap = ray_tpu.get(controller.metrics_snapshot.remote(), timeout=5)
    m = _serve_metrics()
    for name, info in snap.items():
        m["queue_depth"].set(float(info["queue_depth"]),
                             tags={"deployment": name})
        m["replicas"].set(float(info["replicas"]),
                          tags={"deployment": name})


def status() -> Dict[str, Any]:
    """Deployment -> {target, replicas} (reference serve.status)."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {}
    return ray_tpu.get(controller.list_deployments.remote())


def delete(name: str) -> bool:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return False
    return ray_tpu.get(controller.delete_deployment.remote(name))


def get_deployment_handle(name: str) -> DeploymentHandle:
    return _cached_handle(name)


def reconfigure(name: str, user_config: Any) -> bool:
    """Push a new user_config to a live deployment in place (lightweight
    update: no rolling restart).  Returns True if every live replica
    acknowledged; False if some pushes were lost (stragglers converge when
    the reconcile loop replaces them).  Raises KeyError for unknown names."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(
        controller.reconfigure_deployment.remote(name, user_config))


def shutdown() -> None:
    _close_cached_handles()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except (OSError, TimeoutError, ValueError, KeyError, RuntimeError) as e:
        logger.debug("controller teardown best-effort: %s", e)


# ------------------------------------------------------------------ http


@ray_tpu.remote
class _HTTPProxyActor:
    """HTTP ingress (reference HTTPProxyActor, _private/http_proxy.py:250,
    434): an asyncio HTTP/1.1 edge whose request lifecycle is event-driven
    (completion via add_done_callback — no thread parked per request), with
    raw/binary bodies and chunked streaming responses. Implementation:
    serve/http_proxy.py."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        from ray_tpu.serve.http_proxy import AsyncHTTPProxy

        self._server = AsyncHTTPProxy(
            host, port,
            get_handle=_cached_handle,
            get_stream_handle=lambda name, method="__call__": _cached_handle(
                name, method, stream=True))
        self.port = self._server.port

    def get_port(self) -> int:
        return self.port

    def metrics_snapshot(self):
        """This proxy process's serve series, for the driver's exporter."""
        from ray_tpu.util import metrics as metrics_mod

        return metrics_mod.snapshot("ray_tpu_serve_")


def start_http_proxy(port: int = 0):
    """Start the HTTP ingress actor; returns (actor_handle, port)."""
    actor = _HTTPProxyActor.options(
        num_cpus=0, max_concurrency=8, name=PROXY_NAME).remote(port)
    return actor, ray_tpu.get(actor.get_port.remote())


def start_http_proxies_per_node(port: int = 0):
    """One HTTP ingress actor pinned to EVERY alive node (reference
    HTTPProxyActor-per-node, `_private/http_proxy.py:434` /
    `http_state.py`): each proxy binds 0.0.0.0 so an external load balancer
    (or local clients) can reach every node. Returns
    [(node_id_hex, node_host, handle, port)].

    With a fixed `port`, every node listens on the same port (one proxy per
    HOST — in-process test clusters share one host, where only the first
    bind succeeds); with port=0 each proxy picks a free port. Actors are
    created in parallel; nodes that died since the snapshot (or whose bind
    failed) are skipped with a warning rather than hanging the caller."""
    from ray_tpu.core.task_spec import SchedulingStrategy

    started = []
    for n in ray_tpu.nodes():
        if not n.get("alive", True):
            continue
        node_id = n["node_id"]
        host = str(n.get("address", "127.0.0.1")).rsplit(":", 1)[0]
        actor = _HTTPProxyActor.options(
            num_cpus=0, max_concurrency=8,
            name=f"{PROXY_NAME}:{node_id.hex()[:8]}",
            scheduling_strategy=SchedulingStrategy(
                name=None, node_id=node_id)).remote(port, "0.0.0.0")
        started.append((node_id.hex(), host, actor))
    out = []
    for node_hex, host, actor in started:
        try:
            out.append((node_hex, host, actor,
                        ray_tpu.get(actor.get_port.remote(), timeout=60)))
        except Exception as e:
            logger.warning("per-node proxy on %s failed: %s", node_hex[:8], e)
    return out


# ------------------------------------------------------------------ grpc


@ray_tpu.remote
class _GrpcProxyActor:
    """gRPC ingress actor (reference's gRPC proxy role, serve.proto:235):
    a grpc.aio edge exposing /rayserve.Ingress/Predict + PredictStream with
    deployment routing via metadata. Implementation: serve/grpc_ingress.py."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        from ray_tpu.serve.grpc_ingress import GrpcIngress

        self._server = GrpcIngress(
            host, port,
            get_handle=_cached_handle,
            get_stream_handle=lambda name, method="__call__": _cached_handle(
                name, method, stream=True))
        self.port = self._server.port

    def get_port(self) -> int:
        return self.port


def start_grpc_proxy(port: int = 0):
    """Start the gRPC ingress actor; returns (actor_handle, port).
    Requires grpcio (baked into standard images; raises cleanly without)."""
    actor = _GrpcProxyActor.options(
        num_cpus=0, max_concurrency=8, name=GRPC_PROXY_NAME).remote(port)
    return actor, ray_tpu.get(actor.get_port.remote())


# ------------------------------------------------------------------- rpc


@ray_tpu.remote
class _RPCProxyActor:
    """Binary RPC ingress on the framework's native framed protocol —
    the role of the reference's gRPC ingress (`serve.proto:235`) without
    protobuf: clients send `serve_request {deployment, method, payload}`
    and get the pickled result back. Suited to service-to-service calls
    where JSON-over-HTTP overhead matters."""

    def __init__(self, port: int):
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.core.rpc import RpcServer

        proxy = self
        pool = ThreadPoolExecutor(max_workers=16,
                                  thread_name_prefix="serve-rpc")

        def handle(conn, req_id, payload):
            def run():
                try:
                    name = payload["deployment"]
                    method = payload.get("method", "__call__")
                    h = proxy._handles.setdefault(
                        (name, method), DeploymentHandle(name, method))
                    result = ray_tpu.get(
                        h.remote(*payload.get("args", ()),
                                 **payload.get("kwargs", {})),
                        timeout=payload.get("timeout", 60))
                    conn.reply(req_id, result)
                except Exception as e:
                    conn.reply(req_id, f"{e}", is_error=True)

            pool.submit(run)  # keep the rpc loop free for other requests
            return RpcServer.DEFERRED

        self._handles: Dict[tuple, DeploymentHandle] = {}
        self._server = RpcServer(host="127.0.0.1", port=port)
        self._server.register("serve_request", handle)
        self._server.start()
        self.port = self._server.port

    def get_port(self) -> int:
        return self.port


def start_rpc_proxy(port: int = 0):
    """Start the binary RPC ingress; returns (actor_handle, port).

    Client side:
        from ray_tpu.core.rpc import RpcClient
        c = RpcClient(f"127.0.0.1:{port}")
        c.call("serve_request", {"deployment": "Model", "args": (x,)})
    """
    actor = _RPCProxyActor.options(num_cpus=0, max_concurrency=8).remote(port)
    return actor, ray_tpu.get(actor.get_port.remote())
