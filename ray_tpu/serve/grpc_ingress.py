"""gRPC ingress for Serve (reference `src/ray/protobuf/serve.proto:235`,
`serve/_private/grpc_util.py`).

The reference serves user-defined protobuf services over a gRPC proxy next
to HTTP. This edge exposes the equivalent surface as a GENERIC bytes
service — `/rayserve.Ingress/Predict` (unary) and
`/rayserve.Ingress/PredictStream` (server-streaming) — with the target
deployment/method carried in request metadata, so applications bring any
payload encoding (their own protobufs, JSON, raw tensors) without a
codegen step. Built on grpc.aio inside a dedicated loop thread; request
completion and stream items ride the same ownership-layer callbacks as the
HTTP edge (thread-free, no per-stream parking).

Routing metadata: `deployment` (required), `method` (default `__call__`),
`content-type` (`application/json` decodes the request bytes to a JSON
payload; anything else passes raw bytes through). Responses: bytes pass
through; str encodes utf-8; other values JSON-encode.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Tuple

logger = logging.getLogger(__name__)

_REQUEST_TIMEOUT_S = 60.0

SERVICE = "rayserve.Ingress"


def _decode(body: bytes, content_type: str) -> Any:
    if "json" in content_type:
        return json.loads(body) if body else {}
    return body


def _encode(out: Any) -> bytes:
    if isinstance(out, (bytes, bytearray, memoryview)):
        return bytes(out)
    if isinstance(out, str):
        return out.encode()
    return json.dumps({"result": out}).encode()


class GrpcIngress:
    """grpc.aio server on its own loop thread (the HTTP edge's anatomy)."""

    def __init__(self, host: str, port: int, get_handle, get_stream_handle):
        import grpc

        self._get_handle = get_handle
        self._get_stream_handle = get_stream_handle
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="serve-grpc")
        self._loop = asyncio.new_event_loop()
        self.port: int = 0
        started = threading.Event()

        from ray_tpu.serve.edge_util import (await_next_stream_item,
                                             await_ref, fetch_value)

        async def predict(request: bytes, context) -> bytes:
            name, method, payload = self._route(request, context)
            ref = await self._submit(self._get_handle(name, method), payload)
            await await_ref(self._loop, ref, _REQUEST_TIMEOUT_S)
            return _encode(await fetch_value(self._loop, self._pool, ref,
                                             _REQUEST_TIMEOUT_S))

        async def predict_stream(request: bytes, context):
            name, method, payload = self._route(request, context)
            gen = await self._submit(
                self._get_stream_handle(name, method), payload)
            while True:
                if not gen._done:
                    await await_next_stream_item(self._loop, gen,
                                                 _REQUEST_TIMEOUT_S)
                try:
                    ref = next(gen)
                except StopIteration:
                    break
                yield _encode(await fetch_value(self._loop, self._pool, ref,
                                                _REQUEST_TIMEOUT_S))

        def run() -> None:
            asyncio.set_event_loop(self._loop)

            async def serve() -> None:
                handler = grpc.method_handlers_generic_handler(SERVICE, {
                    "Predict": grpc.unary_unary_rpc_method_handler(predict),
                    "PredictStream": grpc.unary_stream_rpc_method_handler(
                        predict_stream),
                })
                self._server = grpc.aio.server()
                self._server.add_generic_rpc_handlers((handler,))
                self.port = self._server.add_insecure_port(f"{host}:{port}")
                await self._server.start()
                started.set()

            self._loop.run_until_complete(serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="serve-grpc-loop",
                                        daemon=True)
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("gRPC ingress failed to start")

    # --------------------------------------------------------------- helpers
    @staticmethod
    def _route(request: bytes, context) -> Tuple[str, str, Any]:
        md = dict(context.invocation_metadata())
        name = md.get("deployment")
        if not name:
            raise ValueError("missing 'deployment' metadata")
        method = md.get("method", "__call__")
        payload = _decode(request, md.get("content-type", "application/json"))
        return name, method, payload

    async def _submit(self, handle, payload):
        if getattr(handle, "_replicas", None):
            return handle.remote(payload)
        return await self._loop.run_in_executor(
            self._pool, handle.remote, payload)

    def stop(self) -> None:
        async def _shutdown() -> None:
            await self._server.stop(grace=None)
            self._loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
        except Exception:
            pass
        self._pool.shutdown(wait=False)
