"""gRPC ingress for Serve (reference `src/ray/protobuf/serve.proto:235`,
`serve/_private/grpc_util.py`).

The reference serves user-defined protobuf services over a gRPC proxy next
to HTTP. This edge exposes the equivalent surface as a GENERIC bytes
service — `/rayserve.Ingress/Predict` (unary) and
`/rayserve.Ingress/PredictStream` (server-streaming) — with the target
deployment/method carried in request metadata, so applications bring any
payload encoding (their own protobufs, JSON, raw tensors) without a
codegen step. Built on grpc.aio inside a dedicated loop thread; request
completion and stream items ride the same ownership-layer callbacks as the
HTTP edge (thread-free, no per-stream parking).

Routing metadata: `deployment` (required), `method` (default `__call__`),
`content-type` (`application/json` decodes the request bytes to a JSON
payload; anything else passes raw bytes through), `timeout-s` (per-request
end-to-end deadline, default `ServeConfig.request_timeout_s`). Responses:
bytes pass through; str encodes utf-8; other values JSON-encode.

Overload robustness mirrors the HTTP edge: the deadline is threaded
through the router into the replica; expiry aborts with
DEADLINE_EXCEEDED, an admission-control shed (typed BackPressureError)
aborts with RESOURCE_EXHAUSTED — both with the typed error name in the
status details.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Tuple

logger = logging.getLogger(__name__)

# backstop past the request deadline (the router's deadline reaper
# resolves the promise AT the deadline; this only fires if that broke)
_EDGE_GRACE_S = 5.0

SERVICE = "rayserve.Ingress"


def _decode(body: bytes, content_type: str) -> Any:
    if "json" in content_type:
        return json.loads(body) if body else {}
    return body


def _encode(out: Any) -> bytes:
    if isinstance(out, (bytes, bytearray, memoryview)):
        return bytes(out)
    if isinstance(out, str):
        return out.encode()
    return json.dumps({"result": out}).encode()


class GrpcIngress:
    """grpc.aio server on its own loop thread (the HTTP edge's anatomy)."""

    def __init__(self, host: str, port: int, get_handle, get_stream_handle):
        import grpc

        self._get_handle = get_handle
        self._get_stream_handle = get_stream_handle
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="serve-grpc")
        self._loop = asyncio.new_event_loop()
        self.port: int = 0
        started = threading.Event()

        from ray_tpu.serve.edge_util import (await_next_stream_item,
                                             await_ref, fetch_value)

        async def _abort_typed(context, e: BaseException):
            """Typed status mapping (the HTTP edge's 504/503 analog)."""
            from ray_tpu.serve.edge_util import typed_error_kind

            kind = typed_error_kind(e)
            if kind == "timeout":
                code = grpc.StatusCode.DEADLINE_EXCEEDED
            elif kind == "shed":
                code = grpc.StatusCode.RESOURCE_EXHAUSTED
            elif isinstance(e, ValueError):
                # bad routing/timeout metadata (the HTTP edge's 400)
                code = grpc.StatusCode.INVALID_ARGUMENT
            else:
                raise e
            await context.abort(code, f"{type(e).__name__}: {e}")

        async def predict(request: bytes, context) -> bytes:
            try:
                name, method, payload, deadline_ts, timeout_s = \
                    self._route(request, context)
                ref = await self._submit(self._get_handle(name, method),
                                         payload, deadline_ts)
                await await_ref(self._loop, ref, timeout_s + _EDGE_GRACE_S)
                return _encode(await fetch_value(
                    self._loop, self._pool, ref, timeout_s + _EDGE_GRACE_S))
            except Exception as e:
                await _abort_typed(context, e)

        async def predict_stream(request: bytes, context):
            try:
                name, method, payload, deadline_ts, timeout_s = \
                    self._route(request, context)
                gen = await self._submit(
                    self._get_stream_handle(name, method), payload,
                    deadline_ts)
                while True:
                    remaining = deadline_ts - time.time()
                    if remaining <= 0:
                        from ray_tpu.core.exceptions import \
                            RequestTimeoutError

                        raise RequestTimeoutError(
                            "stream exceeded its request deadline")
                    if not gen._done:
                        await await_next_stream_item(
                            self._loop, gen, remaining + _EDGE_GRACE_S)
                    try:
                        ref = next(gen)
                    except StopIteration:
                        break
                    yield _encode(await fetch_value(
                        self._loop, self._pool, ref,
                        remaining + _EDGE_GRACE_S))
            except Exception as e:
                await _abort_typed(context, e)

        def run() -> None:
            asyncio.set_event_loop(self._loop)

            async def serve() -> None:
                handler = grpc.method_handlers_generic_handler(SERVICE, {
                    "Predict": grpc.unary_unary_rpc_method_handler(predict),
                    "PredictStream": grpc.unary_stream_rpc_method_handler(
                        predict_stream),
                })
                self._server = grpc.aio.server()
                self._server.add_generic_rpc_handlers((handler,))
                self.port = self._server.add_insecure_port(f"{host}:{port}")
                await self._server.start()
                started.set()

            self._loop.run_until_complete(serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="serve-grpc-loop",
                                        daemon=True)
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("gRPC ingress failed to start")

    # --------------------------------------------------------------- helpers
    @staticmethod
    def _route(request: bytes, context):
        from ray_tpu.serve.config import get_serve_config

        md = dict(context.invocation_metadata())
        name = md.get("deployment")
        if not name:
            raise ValueError("missing 'deployment' metadata")
        method = md.get("method", "__call__")
        payload = _decode(request, md.get("content-type", "application/json"))
        import math

        try:
            timeout_s = float(md.get("timeout-s") or
                              get_serve_config().request_timeout_s)
        except ValueError:
            raise ValueError(f"bad timeout-s metadata: {md.get('timeout-s')!r}")
        if not math.isfinite(timeout_s) or timeout_s <= 0:
            raise ValueError(f"timeout-s must be finite and > 0, "
                             f"got {md.get('timeout-s')!r}")
        return name, method, payload, time.time() + timeout_s, timeout_s

    async def _submit(self, handle, payload, deadline_ts):
        if getattr(handle, "_replicas", None):
            return handle.remote(payload, _deadline_ts=deadline_ts)
        return await self._loop.run_in_executor(
            self._pool,
            lambda: handle.remote(payload, _deadline_ts=deadline_ts))

    def stop(self) -> None:
        async def _shutdown() -> None:
            await self._server.stop(grace=None)
            self._loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
        except Exception:
            pass
        self._pool.shutdown(wait=False)
