"""Asyncio HTTP ingress for Serve (reference `serve/_private/http_proxy.py:250`).

The previous edge was a ThreadingHTTPServer parking one OS thread per
in-flight request on a blocking 60 s `ray_tpu.get`. This proxy is a
stdlib-only asyncio HTTP/1.1 server whose request lifecycle is event-driven
end to end: submission runs on a small executor pool (it can touch sockets),
completion rides the ownership layer's `add_done_callback` (thread-free, the
same mechanism the handle router uses for in-flight accounting), and only
the final value fetch — instant once the object is terminal — touches the
pool again.

Features the reference edge has that the old one lacked:
- raw/binary request bodies (any content type; JSON stays convenient)
- binary/text responses (bytes -> octet-stream, str -> text/plain)
- STREAMING responses: `POST /<deployment>/stream` (or `?stream=1`) iterates
  a num_returns="dynamic" replica generator and relays each item as an HTTP
  chunk as it is produced — token streaming for the LLM engine
  (reference streaming HTTP responses, http_proxy.py + serve handles'
  `options(stream=True)`).
- keep-alive connections.

Overload robustness: every request carries an end-to-end deadline
(`?timeout_s=` query param or `X-Request-Timeout-S` header; default
`ServeConfig.request_timeout_s`) threaded through the router into the
replica. Deadline expiry answers 504 and an admission-control shed — the
router's per-replica in-flight cap, or this proxy's own in-flight cap —
answers 503, both with the typed error name in the JSON body, so a hung or
dying replica can never hold a proxy connection open forever.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qsl, urlparse

from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 512 * 1024 * 1024
# grace past the request deadline before the edge's own await gives up: the
# router's deadline reaper resolves the promise AT the deadline, so this
# backstop only fires if the promise machinery itself is broken
_EDGE_GRACE_S = 5.0


def _error_payload(e: BaseException) -> bytes:
    """JSON error body with the TYPED name — clients and the storm harness
    key on `type`, not the message."""
    return json.dumps({"error": str(e), "type": type(e).__name__}).encode()


def _error_status(e: BaseException) -> int:
    """Map typed serve errors to HTTP statuses (504 deadline, 503 shed,
    404 unmatched app route, 500 everything else)."""
    from ray_tpu.serve.edge_util import typed_error_kind

    return {"route_not_found": 404, "shed": 503,
            "timeout": 504}.get(typed_error_kind(e), 500)


class _BadRequest(Exception):
    pass


class AsyncHTTPProxy:
    """HTTP/1.1 server on a dedicated asyncio loop thread."""

    def __init__(self, host: str, port: int, get_handle, get_stream_handle):
        """`get_handle(name)` / `get_stream_handle(name)` return Serve
        deployment handles (injected so this module stays import-light)."""
        self._get_handle = get_handle
        self._get_stream_handle = get_stream_handle
        # proxy-level admission control: in-flight requests this edge will
        # hold before shedding with 503 (mutated only on the loop thread)
        self._inflight = 0
        # submissions + ready-object fetches; sized generously because every
        # operation on it is short (submit) or instant (terminal-state get).
        # Streams don't park threads here: item arrival is event-driven
        # (add_dynamic_return_callback), so live-stream count is unbounded.
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="serve-http")
        self._loop = asyncio.new_event_loop()
        self.port: int = 0
        started = threading.Event()

        def run() -> None:
            asyncio.set_event_loop(self._loop)

            async def serve() -> None:
                server = await asyncio.start_server(
                    self._handle_conn, host, port)
                self.port = server.sockets[0].getsockname()[1]
                started.set()

            self._loop.run_until_complete(serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="serve-http-loop",
                                        daemon=True)
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("HTTP proxy failed to start")

    # ------------------------------------------------------------ request IO
    async def _read_request(self, reader) -> Optional[dict]:
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.IncompleteReadError):
            return None
        if not line:
            return None
        try:
            method, target, _version = line.decode("latin1").split(None, 2)
        except ValueError:
            raise _BadRequest("malformed request line")
        headers: Dict[str, str] = {}
        total = len(line)
        while True:
            line = await reader.readline()
            total += len(line)
            if total > _MAX_HEADER_BYTES:
                raise _BadRequest("headers too large")
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0) or 0)
        if length > _MAX_BODY_BYTES:
            raise _BadRequest("body too large")
        try:
            body = await reader.readexactly(length) if length else b""
        except (ConnectionError, asyncio.IncompleteReadError):
            return None  # client aborted mid-body: routine disconnect
        return {"method": method.upper(), "target": target,
                "headers": headers, "body": body,
                "close": headers.get("connection", "").lower() == "close"}

    @staticmethod
    def _response(status: int, body: bytes, content_type: str,
                  close: bool) -> bytes:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error", 503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(status, "")
        return (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: {'close' if close else 'keep-alive'}\r\n"
                "\r\n").encode("latin1") + body

    @staticmethod
    def _encode_result(out: Any) -> Tuple[bytes, str]:
        if isinstance(out, (bytes, bytearray, memoryview)):
            return bytes(out), "application/octet-stream"
        if isinstance(out, str):
            return out.encode(), "text/plain; charset=utf-8"
        return json.dumps({"result": out}).encode(), "application/json"

    # ------------------------------------------------------------- lifecycle
    async def _handle_conn(self, reader, writer) -> None:
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except _BadRequest as e:
                    writer.write(self._response(
                        400, json.dumps({"error": str(e)}).encode(),
                        "application/json", True))
                    await writer.drain()
                    break
                if req is None:
                    break
                try:
                    await self._dispatch(req, writer)
                except (ConnectionError, asyncio.IncompleteReadError):
                    break
                if req["close"]:
                    break
        except Exception:
            logger.exception("http connection failed")
        finally:
            try:
                writer.close()
            except OSError:
                pass

    def _parse_target(self, req: dict):
        """Route `/<deployment>[/<method>]` with `?stream=1` selecting the
        chunked streaming path (the method must return a generator).
        Returns (name, method, payload, stream, subpath, query, timeout_s):
        app-ingress deployments re-route on subpath at dispatch time; the
        per-request deadline comes from `?timeout_s=` / the
        `X-Request-Timeout-S` header, default ServeConfig.request_timeout_s."""
        from ray_tpu.serve.config import get_serve_config

        parsed = urlparse(req["target"])
        parts = [p for p in parsed.path.split("/") if p]
        query = dict(parse_qsl(parsed.query))
        stream = query.pop("stream", "0") in ("1", "true")
        import math

        raw_timeout = (query.pop("timeout_s", None)
                       or req["headers"].get("x-request-timeout-s"))
        try:
            timeout_s = float(raw_timeout) if raw_timeout else \
                get_serve_config().request_timeout_s
        except ValueError:
            raise _BadRequest(f"bad timeout_s: {raw_timeout!r}")
        # NaN passes a naive <= 0 check and poisons the deadline math;
        # inf would park a reaper entry forever
        if not math.isfinite(timeout_s) or timeout_s <= 0:
            raise _BadRequest(f"timeout_s must be finite and > 0, "
                              f"got {raw_timeout!r}")
        if not parts:
            raise _BadRequest("no deployment in path")
        name = parts[0]
        method = parts[1] if len(parts) > 1 else "__call__"
        subpath = "/" + "/".join(parts[1:])
        if req["method"] == "GET":
            payload: Any = query
        else:
            ctype = req["headers"].get("content-type", "application/json")
            if "json" in ctype:
                try:
                    payload = json.loads(req["body"]) if req["body"] else {}
                except ValueError as e:
                    raise _BadRequest(f"bad JSON body: {e}")
            elif ("form-urlencoded" in ctype or ctype.startswith("text/")):
                # clients (urllib!) that omit an explicit JSON content type
                # still overwhelmingly send JSON; fall back to raw on parse
                # failure instead of rejecting
                try:
                    payload = json.loads(req["body"]) if req["body"] else {}
                except ValueError:
                    payload = req["body"]
            else:
                payload = req["body"]  # raw/binary passthrough
        return name, method, payload, stream, subpath, query, timeout_s

    async def _is_app_ingress(self, name: str) -> bool:
        """Whether `name` is an @serve.ingress app deployment. The flag
        stays CURRENT: the one-shot refresh seeds it and the handle's
        push-driven refresher keeps tracking redeploys (a deployment can
        gain or lose its app between versions)."""
        call_handle = self._get_handle(name, "__call__")
        if not hasattr(call_handle, "_app_ingress"):
            await self._loop.run_in_executor(
                self._pool, lambda: call_handle._refresh(block=False))
            call_handle._ensure_refresher()
        return getattr(call_handle, "_app_ingress", False)

    async def _dispatch(self, req: dict, writer) -> None:
        from ray_tpu.core.exceptions import BackPressureError
        from ray_tpu.serve.api import _serve_metrics
        from ray_tpu.serve.config import get_serve_config
        from ray_tpu.serve.edge_util import await_ref, fetch_value

        t0 = time.monotonic()
        try:
            name, method, payload, stream, subpath, query, timeout_s = \
                self._parse_target(req)
        except _BadRequest as e:
            writer.write(self._response(
                400, json.dumps({"error": str(e)}).encode(),
                "application/json", req["close"]))
            await writer.drain()
            return
        deadline_ts = time.time() + timeout_s
        # proxy-level admission control (shed site #1): bound the requests
        # this edge holds open so a storm degrades to fast 503s here
        # before it can exhaust proxy memory/file descriptors
        if self._inflight >= get_serve_config().proxy_max_inflight:
            e = BackPressureError(
                f"proxy at in-flight cap "
                f"({get_serve_config().proxy_max_inflight}); request shed")
            writer.write(self._response(
                503, _error_payload(e), "application/json", req["close"]))
            await writer.drain()
            return
        self._inflight += 1
        # Ingress span roots the request's trace. The ids are minted HERE
        # (explicitly, not via thread-local start_trace): _dispatch is a
        # coroutine, and thread-local context must never span an await — it
        # is adopted only inside the synchronous submit windows below.
        ing_ctx = None
        t_ing = 0.0
        if tracing.enabled():
            ing_ctx = (tracing.new_id(), tracing.new_id())
            t_ing = tracing.now_us()
        # no requests.inc here: the handle's remote() counts it (this
        # process), exactly as the edge always has
        try:
            # app-ingress deployments take the FULL request envelope on
            # __call__ and route the subpath in-replica (serve.ingress)
            app_ingress = await self._is_app_ingress(name)
            if stream:
                if app_ingress:
                    raise _BadRequest(
                        "app-ingress deployments do not support ?stream=1")
                await self._dispatch_stream(name, method, payload, req,
                                            writer, deadline_ts,
                                            trace_ctx=ing_ctx)
            else:
                if app_ingress:
                    method = "__call__"
                    payload = {
                        "method": req["method"], "path": subpath,
                        "query": query,
                        "payload": (None if req["method"] == "GET"
                                    else payload),
                    }
                handle = self._get_handle(name, method)
                if getattr(handle, "_replicas", None):
                    # warm handle: submission is sample + one socket send —
                    # cheaper than a thread hop (synchronous window: the
                    # ingress ctx is safe to adopt, no await inside)
                    with tracing.ctx_scope(ing_ctx):
                        ref = handle.remote(payload,
                                            _deadline_ts=deadline_ts)
                else:
                    def _submit():
                        with tracing.ctx_scope(ing_ctx):
                            return handle.remote(payload,
                                                 _deadline_ts=deadline_ts)
                    ref = await self._loop.run_in_executor(
                        self._pool, _submit)
                # the router's deadline reaper resolves the promise AT the
                # deadline; the edge timeout is only the backstop behind it
                try:
                    await await_ref(self._loop, ref,
                                    timeout_s + _EDGE_GRACE_S)
                    out = await fetch_value(self._loop, self._pool, ref,
                                            timeout_s + _EDGE_GRACE_S)
                    body, ctype = self._encode_result(out)
                    writer.write(self._response(200, body, ctype,
                                                req["close"]))
                    await writer.drain()
                except (ConnectionError, asyncio.CancelledError):
                    # client went away while the request was in flight:
                    # cancel the replica attempt through the router so the
                    # replica stops computing a result nobody will read
                    from ray_tpu.serve.api import cancel_inflight

                    cancel_inflight(ref)
                    raise
        except _BadRequest as e:
            writer.write(self._response(
                400, json.dumps({"error": str(e)}).encode(),
                "application/json", req["close"]))
            await writer.drain()
        except Exception as e:
            _serve_metrics()["errors"].inc(tags={"deployment": name})
            # typed mapping: 504 on deadline expiry, 503 on shed, 404 on
            # unmatched app routes, 500 otherwise — with the error type
            # name in the body (works for both the live exception and its
            # deserialized-from-the-replica form)
            writer.write(self._response(
                _error_status(e), _error_payload(e),
                "application/json", req["close"]))
            await writer.drain()
        finally:
            self._inflight -= 1
            _serve_metrics()["latency"].observe(
                time.monotonic() - t0, tags={"deployment": name})
            if ing_ctx is not None:
                tracing.add_complete(
                    f"ingress::{name}", "serve_ingress",
                    t_ing, tracing.now_us() - t_ing,
                    trace_id=ing_ctx[0], span_id=ing_ctx[1], parent_id="",
                    deployment=name, method=req.get("method", ""))

    async def _dispatch_stream(self, name: str, method: str, payload: Any,
                               req: dict, writer,
                               deadline_ts: Optional[float] = None,
                               trace_ctx=None) -> None:
        """Chunked-encoding relay of a streaming deployment: each object the
        replica's generator yields becomes one HTTP chunk as soon as it is
        reported — tokens reach the client while the model still decodes.
        Item arrival rides the same add_done_callback mechanism as the
        non-streaming path (reference http_proxy.py's async streaming
        model), so there is NO thread-per-live-stream and no stream cap.
        The request deadline bounds the WHOLE stream: when it expires
        mid-stream, a typed error chunk + clean terminator go out instead
        of the connection hanging on a stalled replica."""
        from ray_tpu.serve.config import get_serve_config
        from ray_tpu.serve.edge_util import (await_next_stream_item,
                                             fetch_value)

        if deadline_ts is None:
            deadline_ts = time.time() + get_serve_config().request_timeout_s

        def _remaining() -> float:
            return max(0.001, deadline_ts - time.time() + _EDGE_GRACE_S)

        # submit BEFORE the 200 goes out: submission failures (no replicas,
        # unknown deployment, back-pressure shed) still produce a clean
        # typed 503/500 via the caller
        handle = self._get_stream_handle(name, method)
        if getattr(handle, "_replicas", None):
            with tracing.ctx_scope(trace_ctx):
                gen = handle.remote(payload, _deadline_ts=deadline_ts)
        else:
            def _submit():
                with tracing.ctx_scope(trace_ctx):
                    return handle.remote(payload, _deadline_ts=deadline_ts)
            gen = await self._loop.run_in_executor(self._pool, _submit)
        writer.write((
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: application/x-ndjson\r\n"
            "Transfer-Encoding: chunked\r\n"
            f"Connection: {'close' if req['close'] else 'keep-alive'}\r\n"
            "\r\n").encode("latin1"))
        await writer.drain()

        # Once chunked 200 headers are out, an HTTP 500 can never follow —
        # writing one mid-body would corrupt framing and desync keep-alive.
        # Errors become a final error chunk + a CLEAN chunk terminator.
        try:
            while True:
                if time.time() >= deadline_ts:
                    from ray_tpu.core.exceptions import RequestTimeoutError

                    raise RequestTimeoutError(
                        "stream exceeded its request deadline")
                if not gen._done:
                    await await_next_stream_item(self._loop, gen,
                                                 _remaining())
                try:
                    ref = next(gen)
                except StopIteration:
                    break
                item = await fetch_value(self._loop, self._pool, ref,
                                         _remaining())
                if isinstance(item, (bytes, bytearray, memoryview)):
                    chunk = bytes(item)
                elif isinstance(item, str):
                    chunk = item.encode()
                else:
                    chunk = json.dumps(item).encode() + b"\n"
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                await writer.drain()
        except Exception as e:
            from ray_tpu.serve.api import _serve_metrics

            _serve_metrics()["errors"].inc(tags={"deployment": name})
            err = json.dumps({"error": str(e),
                              "type": type(e).__name__}).encode() + b"\n"
            writer.write(f"{len(err):x}\r\n".encode() + err + b"\r\n")
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    def stop(self) -> None:
        try:
            self._loop.call_soon_threadsafe(self._loop.stop)
        except RuntimeError:
            pass  # loop already closed
        self._pool.shutdown(wait=False)
