"""App ingress: mount a multi-route application on a deployment.

Mirrors the reference's `@serve.ingress(fastapi_app)` (python/ray/serve/
api.py:160): a deployment whose HTTP surface is a ROUTED APP — path
patterns with parameters, per-route HTTP methods, and middleware hooks —
instead of the default `/<deployment>/<method>` convention. The app is a
dependency-free FastAPI-shaped router: `@app.get("/items/{item_id}")`
handlers, `@app.middleware` wrappers, 404s for unmatched routes.

The HTTP edge detects app-mounted deployments through the controller's
replica info and forwards the FULL sub-path request envelope; dispatch
(routing, parameter extraction, middleware) runs IN the replica, so every
replica scales the whole app."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["App", "Request", "RouteNotFound", "ingress"]


class RouteNotFound(Exception):
    """No route matched (the edge maps this to HTTP 404)."""


class Request:
    """The per-request envelope a routed handler receives."""

    __slots__ = ("method", "path", "query", "payload", "path_params",
                 "headers")

    def __init__(self, method: str = "GET", path: str = "/",
                 query: Optional[Dict[str, str]] = None, payload: Any = None,
                 path_params: Optional[Dict[str, str]] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.method = method.upper()
        self.path = path or "/"
        self.query = dict(query or {})
        self.payload = payload
        self.path_params = dict(path_params or {})
        self.headers = dict(headers or {})


def _compile_pattern(path: str) -> List[Tuple[str, str]]:
    """'/items/{item_id}' -> [("lit","items"), ("param","item_id")]."""
    parts = []
    for seg in path.split("/"):
        if not seg:
            continue
        if seg.startswith("{") and seg.endswith("}"):
            parts.append(("param", seg[1:-1]))
        else:
            parts.append(("lit", seg))
    return parts


class App:
    """Route + middleware registry (FastAPI-shaped, stdlib-only)."""

    def __init__(self):
        self._routes: List[Tuple[str, List[Tuple[str, str]], Callable]] = []
        self._middlewares: List[Callable] = []

    # ------------------------------------------------------------ decorators
    def route(self, path: str, methods=("GET", "POST")):
        def deco(fn):
            takes_self = _takes_self(fn)  # once, at registration
            for m in methods:
                self._routes.append(
                    (m.upper(), _compile_pattern(path), fn, takes_self))
            return fn

        return deco

    def get(self, path: str):
        return self.route(path, methods=("GET",))

    def post(self, path: str):
        return self.route(path, methods=("POST",))

    def put(self, path: str):
        return self.route(path, methods=("PUT",))

    def delete(self, path: str):
        return self.route(path, methods=("DELETE",))

    def middleware(self, fn: Callable) -> Callable:
        """`fn(request, call_next) -> response` wrappers, outermost first
        (reference Starlette middleware model)."""
        self._middlewares.append(fn)
        return fn

    # -------------------------------------------------------------- dispatch
    def match(self, method: str, path: str):
        """(handler, path_params, takes_self) or None."""
        segs = [s for s in path.split("/") if s]
        for m, pattern, fn, takes_self in self._routes:
            if m != method.upper() or len(pattern) != len(segs):
                continue
            params: Dict[str, str] = {}
            ok = True
            for (kind, val), seg in zip(pattern, segs):
                if kind == "lit":
                    if val != seg:
                        ok = False
                        break
                else:
                    params[val] = seg
            if ok:
                return fn, params, takes_self
        return None

    def dispatch(self, instance: Any, request: Request) -> Any:
        hit = self.match(request.method, request.path)
        if hit is None:
            raise RouteNotFound(
                f"{request.method} {request.path} matched no route")
        fn, params, takes_self = hit
        request.path_params = params

        def call_handler(req: Request) -> Any:
            if instance is not None and takes_self:
                return fn(instance, req, **req.path_params)
            return fn(req, **req.path_params)

        call = call_handler
        for mw in reversed(self._middlewares):
            call = (lambda req, _mw=mw, _next=call: _mw(req, _next))
        return call(request)


def _takes_self(fn: Callable) -> bool:
    import inspect

    try:
        params = list(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        return False
    return bool(params) and params[0] == "self"


def ingress(app: App):
    """Class decorator mounting `app` as the deployment's request surface
    (reference serve.ingress, python/ray/serve/api.py:160). The wrapped
    class's `__call__` receives the edge's request envelope and dispatches
    through the app's routes + middleware."""

    def deco(cls):
        if not isinstance(cls, type):
            raise TypeError("serve.ingress decorates a class (put it UNDER "
                            "@serve.deployment)")

        def __call__(self, request: Any) -> Any:
            if not isinstance(request, dict):
                raise TypeError(
                    "app-ingress deployments take the edge's request "
                    "envelope; call them over HTTP or pass a dict like "
                    '{"method": "GET", "path": "/..."}')
            return app.dispatch(self, Request(**request))

        cls.__call__ = __call__
        cls._serve_app = app
        cls._serve_app_ingress = True
        return cls

    return deco
