from ray_tpu.serve.api import (
    deployment,
    run,
    shutdown,
    status,
    delete,
    get_deployment_handle,
    start_http_proxy,
    start_http_proxies_per_node,
    start_grpc_proxy,
    start_rpc_proxy,
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
)
from ray_tpu.serve.config import deploy_config_file, load_config
from ray_tpu.serve.ingress import App, Request, RouteNotFound, ingress
from ray_tpu.serve.batching import batch
