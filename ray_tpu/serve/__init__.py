from ray_tpu.serve.api import (
    deployment,
    run,
    shutdown,
    get_deployment_handle,
    start_http_proxy,
    Deployment,
    DeploymentHandle,
)
