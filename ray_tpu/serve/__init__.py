from ray_tpu.serve.api import (
    deployment,
    run,
    shutdown,
    status,
    delete,
    get_deployment_handle,
    reconfigure,
    start_http_proxy,
    start_http_proxies_per_node,
    start_grpc_proxy,
    start_rpc_proxy,
    router_stats,
    reset_router_stats,
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
)
from ray_tpu.serve.config import (
    ServeConfig,
    deploy_config_file,
    get_serve_config,
    load_config,
    set_serve_config,
)
from ray_tpu.serve.ingress import App, Request, RouteNotFound, ingress
from ray_tpu.serve.batching import batch
