"""Serve configuration: runtime robustness knobs + declarative deploy.

Runtime knobs (`ServeConfig`): the serving plane's overload/robustness
parameters — end-to-end request deadline, admission-control caps, the
failover retry budget, and the replica drain deadline. Env-overridable per
process as `RAY_TPU_SERVE_<NAME>` (the core `Config` pattern), so the
controller/proxy/replica worker processes a raylet spawns inherit
overrides naturally.

Declarative deploy (reference `python/ray/serve/schema.py` + `serve
deploy` in `python/ray/serve/scripts.py`), YAML or JSON:

    applications:
      - name: my_app              # optional; defaults to the root deployment
        import_path: pkg.mod:app  # module attr holding a (bound) Deployment
        deployments:              # optional per-deployment overrides
          - name: Model
            num_replicas: 3

`deploy_config_file` imports each application's root deployment, applies
overrides, and `serve.run`s it.
"""

from __future__ import annotations

import importlib
import os
from dataclasses import dataclass, fields
from typing import Any, Dict, List

from ray_tpu.serve import api as serve_api


@dataclass
class ServeConfig:
    """Serve-plane robustness knobs (reference: serve's
    `request_timeout_s` / `max_queued_requests` / drain semantics)."""

    # Default end-to-end deadline for a serve request (ingress parse ->
    # replica completion). Every request carries a deadline: expired ones
    # resolve with a typed RequestTimeoutError instead of hanging.
    request_timeout_s: float = 60.0
    # Rolling-update / downscale drain: a displaced replica keeps serving
    # its in-flight requests until idle, killed unconditionally after this
    # deadline. (Was a hardcoded 30.0 in the rolling-update path.)
    drain_deadline_s: float = 30.0
    # Admission control at the router: a replica with this many in-flight
    # requests (tracked client-side, the same counts power-of-two routing
    # uses) stops being eligible; when EVERY replica is at the cap the
    # request is shed with a typed BackPressureError (HTTP 503).
    max_queue_per_replica: int = 32
    # Admission control at the ingress: concurrent in-flight requests one
    # proxy will hold before shedding (bounds proxy memory under a storm).
    proxy_max_inflight: int = 2048
    # Mid-request failover: how many times the router re-routes an
    # idempotent request after a replica death / severed replica link
    # before surfacing the typed error. 0 disables failover.
    request_retry_budget: int = 2
    # Full-jitter backoff between failover attempts (util/backoff.py).
    retry_backoff_base_ms: float = 20.0
    retry_backoff_cap_ms: float = 500.0

    def __post_init__(self):
        for f in fields(self):
            env = os.environ.get(f"RAY_TPU_SERVE_{f.name.upper()}")
            if env is not None:
                typ = type(getattr(self, f.name))
                setattr(self, f.name,
                        typ(env) if typ is not bool
                        else env.lower() in ("1", "true", "yes", "on"))


_serve_config: ServeConfig | None = None


def get_serve_config() -> ServeConfig:
    global _serve_config
    if _serve_config is None:
        _serve_config = ServeConfig()
    return _serve_config


def set_serve_config(**overrides) -> ServeConfig:
    """In-process overrides (tests, embedded drivers). Worker processes
    read env (`RAY_TPU_SERVE_*`) instead — set those before `init()` so
    spawned controller/replica/proxy processes inherit them."""
    cfg = get_serve_config()
    for k, v in overrides.items():
        if not hasattr(cfg, k):
            raise ValueError(f"unknown serve config field {k!r}")
        setattr(cfg, k, v)
    return cfg


def reset_serve_config() -> None:
    global _serve_config
    _serve_config = None


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict) or "applications" not in cfg:
        raise ValueError(f"{path}: expected a mapping with 'applications'")
    return cfg


def _import_target(import_path: str) -> serve_api.Deployment:
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must be 'module.path:attribute'")
    mod_name, attr = import_path.split(":", 1)
    target = getattr(importlib.import_module(mod_name), attr)
    if not isinstance(target, serve_api.Deployment):
        raise TypeError(f"{import_path} is {type(target)}, not a Deployment")
    return target


def _apply_overrides(root: serve_api.Deployment,
                     overrides: List[Dict[str, Any]]) -> serve_api.Deployment:
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"}
               for o in overrides}
    # memoized by identity: a diamond graph's shared node must stay one
    # object, or serve.run sees two same-named deployments and rejects it
    rewritten: Dict[int, serve_api.Deployment] = {}
    in_progress: set = set()

    def rewrite(d: serve_api.Deployment) -> serve_api.Deployment:
        if id(d) in rewritten:
            return rewritten[id(d)]
        if id(d) in in_progress:
            raise ValueError(f"deployment graph has a cycle at {d.name!r}")
        in_progress.add(id(d))
        new_args = tuple(rewrite(a) if isinstance(a, serve_api.Deployment)
                         else a for a in d.init_args)
        new_kwargs = {k: rewrite(v) if isinstance(v, serve_api.Deployment)
                      else v for k, v in (d.init_kwargs or {}).items()} or None
        out = d.options(init_args=new_args, init_kwargs=new_kwargs)
        if out.name in by_name:
            out = out.options(**by_name[out.name])
        in_progress.discard(id(d))
        rewritten[id(d)] = out
        return out

    return rewrite(root)


def deploy_config(cfg: Dict[str, Any]) -> Dict[str, str]:
    """Deploy every application in an in-memory config dict (the REST
    `PUT /api/serve/applications` body — reference `serve deploy` REST
    mode); returns {app_name: root deployment name}."""
    if not isinstance(cfg, dict) or "applications" not in cfg:
        raise ValueError("expected a mapping with 'applications'")
    deployed: Dict[str, str] = {}
    for app in cfg["applications"]:
        root = _import_target(app["import_path"])
        if app.get("deployments"):
            root = _apply_overrides(root, app["deployments"])
        serve_api.run(root, name=app.get("name", root.name))
        deployed[app.get("name", root.name)] = root.name
    return deployed


def deploy_config_file(path: str) -> Dict[str, Any]:
    """Deploy every application in the config file; returns {app_name: root}."""
    return deploy_config(load_config(path))
