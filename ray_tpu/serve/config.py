"""Declarative Serve config (reference `python/ray/serve/schema.py` +
`serve deploy` in `python/ray/serve/scripts.py`).

Schema (YAML or JSON):

    applications:
      - name: my_app              # optional; defaults to the root deployment
        import_path: pkg.mod:app  # module attr holding a (bound) Deployment
        deployments:              # optional per-deployment overrides
          - name: Model
            num_replicas: 3

`deploy_config_file` imports each application's root deployment, applies
overrides, and `serve.run`s it.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List

from ray_tpu.serve import api as serve_api


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict) or "applications" not in cfg:
        raise ValueError(f"{path}: expected a mapping with 'applications'")
    return cfg


def _import_target(import_path: str) -> serve_api.Deployment:
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must be 'module.path:attribute'")
    mod_name, attr = import_path.split(":", 1)
    target = getattr(importlib.import_module(mod_name), attr)
    if not isinstance(target, serve_api.Deployment):
        raise TypeError(f"{import_path} is {type(target)}, not a Deployment")
    return target


def _apply_overrides(root: serve_api.Deployment,
                     overrides: List[Dict[str, Any]]) -> serve_api.Deployment:
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"}
               for o in overrides}
    # memoized by identity: a diamond graph's shared node must stay one
    # object, or serve.run sees two same-named deployments and rejects it
    rewritten: Dict[int, serve_api.Deployment] = {}
    in_progress: set = set()

    def rewrite(d: serve_api.Deployment) -> serve_api.Deployment:
        if id(d) in rewritten:
            return rewritten[id(d)]
        if id(d) in in_progress:
            raise ValueError(f"deployment graph has a cycle at {d.name!r}")
        in_progress.add(id(d))
        new_args = tuple(rewrite(a) if isinstance(a, serve_api.Deployment)
                         else a for a in d.init_args)
        new_kwargs = {k: rewrite(v) if isinstance(v, serve_api.Deployment)
                      else v for k, v in (d.init_kwargs or {}).items()} or None
        out = d.options(init_args=new_args, init_kwargs=new_kwargs)
        if out.name in by_name:
            out = out.options(**by_name[out.name])
        in_progress.discard(id(d))
        rewritten[id(d)] = out
        return out

    return rewrite(root)


def deploy_config(cfg: Dict[str, Any]) -> Dict[str, str]:
    """Deploy every application in an in-memory config dict (the REST
    `PUT /api/serve/applications` body — reference `serve deploy` REST
    mode); returns {app_name: root deployment name}."""
    if not isinstance(cfg, dict) or "applications" not in cfg:
        raise ValueError("expected a mapping with 'applications'")
    deployed: Dict[str, str] = {}
    for app in cfg["applications"]:
        root = _import_target(app["import_path"])
        if app.get("deployments"):
            root = _apply_overrides(root, app["deployments"])
        serve_api.run(root, name=app.get("name", root.name))
        deployed[app.get("name", root.name)] = root.name
    return deployed


def deploy_config_file(path: str) -> Dict[str, Any]:
    """Deploy every application in the config file; returns {app_name: root}."""
    return deploy_config(load_config(path))
