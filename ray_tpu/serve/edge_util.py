"""Shared completion plumbing for the Serve edges (HTTP + gRPC).

Both edges run a dedicated asyncio loop thread and resolve request
lifecycles through the ownership layer's callbacks — object completion via
`add_done_callback`, stream items via `add_dynamic_return_callback` — so
no thread is ever parked per in-flight request or live stream. This module
is the single home for that plumbing; the edges stay thin."""

from __future__ import annotations

import asyncio
from typing import Any, Optional


def typed_error_kind(e: BaseException) -> Optional[str]:
    """Classify a serve-plane error for edge status mapping: "timeout"
    (end-to-end deadline spent), "shed" (admission control), or
    "route_not_found" (app ingress); None for everything else. One home
    for the isinstance-plus-type-name check — the NAME fallback matters
    because an error deserialized from a replica process must map the
    same as the live class."""
    from ray_tpu.core.exceptions import (BackPressureError, GetTimeoutError,
                                         RequestTimeoutError)

    name = type(e).__name__
    if (isinstance(e, (RequestTimeoutError, GetTimeoutError,
                       asyncio.TimeoutError))
            or name in ("RequestTimeoutError", "GetTimeoutError")):
        return "timeout"
    if isinstance(e, BackPressureError) or name == "BackPressureError":
        return "shed"
    try:
        from ray_tpu.serve.ingress import RouteNotFound

        if isinstance(e, RouteNotFound) or name == "RouteNotFound":
            return "route_not_found"
    except ImportError:
        pass
    return None


async def await_ref(loop, ref, timeout: float) -> None:
    """Resolve when the ownership layer reports `ref` terminal."""
    from ray_tpu.core.api import _global_worker

    fut = loop.create_future()

    def done() -> None:
        try:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))
        except RuntimeError:
            pass  # loop already stopped

    _global_worker().add_done_callback(ref, done)
    await asyncio.wait_for(fut, timeout=timeout)


async def await_next_stream_item(loop, gen, timeout: float) -> None:
    """Resolve when the generator's next item (or terminal state) is
    reported — `next(gen)` is then guaranteed non-blocking."""
    from ray_tpu.core import worker as _worker_mod

    fut = loop.create_future()

    def ready() -> None:
        try:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))
        except RuntimeError:
            pass

    _worker_mod.current_worker().add_dynamic_return_callback(
        gen._task_id, gen._i, ready)
    await asyncio.wait_for(fut, timeout=timeout)


async def fetch_value(loop, pool, ref, timeout: float) -> Any:
    """Fetch a terminal object's value: inline results resolve on the
    loop; plasma results (a blocking pull) hop to the pool."""
    import ray_tpu
    from ray_tpu.core.api import _global_worker

    out, ok = _global_worker().try_get_local(ref)
    if not ok:
        out = await loop.run_in_executor(
            pool, lambda: ray_tpu.get(ref, timeout=timeout))
    return out
