"""Traffic-storm chaos harness for the serve plane.

Drives sustained synthetic load at a configurable multiple of a
deployment's estimated capacity (default ~4x) against a multi-replica
autoscaling deployment while chaos runs underneath it: PR 3's seeded
`FaultInjector` drops/severs router->replica submissions at the named
`serve_replica_call` boundary, and a kill loop hard-kills a live replica
every few seconds (the health check replaces it; in-flight requests fail
over). The harness then asserts the serve plane's overload contract:

  EVERY submitted request resolves — as a result, a typed
  `RequestTimeoutError`, or a typed `BackPressureError` shed — within its
  deadline (+ grace). Zero hung requests, ever.

Results (accepted/shed/retried counts, p50/p99 latency of accepted
requests, the injection seed) are written as a tracked JSON artifact
(SERVESTORM_r09.json). Run directly:

    python -m ray_tpu.serve.storm            # 30 s storm, writes artifact
    python -m ray_tpu.serve.storm --quick    # ~6 s CI profile
"""

from __future__ import annotations

import json
import logging
import os as _os
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_ARTIFACT = "SERVESTORM_r09.json"
HEADFAIL_ARTIFACT = "HEADFAIL_r11.json"
DEFAULT_FAULT_SPEC = "drop:serve_replica_call:0.02"


@dataclass
class StormProfile:
    """One storm's shape. Capacity is estimated as
    `num_replicas * replica_concurrency / service_time_s`; the offered
    rate is `overload * capacity`."""

    duration_s: float = 30.0
    overload: float = 4.0
    request_timeout_s: float = 2.0
    service_time_s: float = 0.1
    num_replicas: int = 2
    max_replicas: int = 4
    replica_concurrency: int = 4
    max_queue_per_replica: int = 8
    retry_budget: int = 3
    kill_period_s: float = 5.0
    fault_spec: str = DEFAULT_FAULT_SPEC
    seed: int = 0
    submitter_threads: int = 4
    resolve_grace_s: float = 10.0

    @property
    def capacity_rps(self) -> float:
        # the controller floors replica max_concurrency at 4 — use the
        # effective value so "4x capacity" means what it says. With the
        # defaults the offered rate exceeds even the fully-autoscaled
        # (max_replicas) capacity 2x, so overload persists through scale-up.
        return (self.num_replicas * max(4, self.replica_concurrency)
                / self.service_time_s)

    @property
    def offered_rps(self) -> float:
        return self.overload * self.capacity_rps


QUICK_PROFILE = dict(duration_s=6.0, kill_period_s=2.0)
# --kill-head needs a window on BOTH sides of the promotion; the lease TTL
# is squeezed so expiry->promotion fits the CI budget
KILLHEAD_QUICK_PROFILE = dict(duration_s=10.0, kill_period_s=3.0)


@dataclass
class _Outcomes:
    submitted: int = 0
    accepted: int = 0       # resolved with a result
    shed: int = 0           # typed BackPressureError (router or submit)
    timeout: int = 0        # typed RequestTimeoutError / GetTimeoutError
    replica_death: int = 0  # typed ActorDiedError & co past the budget
    other_error: int = 0
    hung: int = 0           # never resolved: the contract violation
    latencies_ms: List[float] = field(default_factory=list)


from ray_tpu.util import tracing as _tracing  # noqa: E402
from ray_tpu.util.stats import percentile as _percentile  # noqa: E402


class LoadGenerator:
    """Paced open-loop load generator against a serve handle (the storm's
    submit/collect machinery, extracted so other benches can reuse it):
    `threads` submitter threads offer `rps` total, a collector thread
    classifies every resolution into typed outcome buckets with accepted-
    request latencies, and `stop_and_drain()` blocks until every submitted
    request resolves (result / typed shed / typed timeout) or the grace
    expires — the remainder is `hung`, the contract violation.

    The storm harness runs one of these with a kill loop + fault injector
    underneath; servebench runs one clean for p50/p99 latency rows."""

    def __init__(self, handle, *, rps: float, request_timeout_s: float,
                 payload_fn=None, threads: int = 4,
                 rng: Optional[random.Random] = None,
                 resolve_grace_s: float = 10.0, trace: bool = False):
        from ray_tpu.core.api import _global_worker

        self.handle = handle
        self.rps = rps
        self.request_timeout_s = request_timeout_s
        self.payload_fn = payload_fn or (lambda idx, i: (idx, i))
        self.threads = threads
        self.rng = rng or random.Random(0)
        self.resolve_grace_s = resolve_grace_s
        # trace=True: each request roots its own trace and accepted
        # requests record their trace_id — the traced storm asserts every
        # one of those resolves to a complete cross-process span chain
        self.trace = trace
        self.trace_ids: List[str] = []  # accepted requests only
        self.outcomes = _Outcomes()
        self.elapsed_s = 0.0
        self._worker = _global_worker()
        self._lock = threading.Lock()
        self._done_q: "queue.Queue" = queue.Queue()
        self._outstanding = threading.Semaphore(0)  # one release/resolution
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._collector_t: Optional[threading.Thread] = None
        self._t_start = 0.0

    @staticmethod
    def classify(err: Optional[BaseException]) -> str:
        from ray_tpu.core.exceptions import (ActorDiedError,
                                             BackPressureError,
                                             GetTimeoutError,
                                             RequestTimeoutError,
                                             WorkerCrashedError)

        if err is None:
            return "accepted"
        if isinstance(err, BackPressureError):
            return "shed"
        if isinstance(err, (RequestTimeoutError, GetTimeoutError)):
            return "timeout"
        if isinstance(err, (ActorDiedError, WorkerCrashedError,
                            ConnectionError)):
            return "replica_death"
        return "other_error"

    def _collector(self) -> None:
        import ray_tpu

        while True:
            item = self._done_q.get()
            if item is None:
                return
            ref, t0, t1, trace_id = item
            err = None
            try:
                ray_tpu.get(ref, timeout=5)  # terminal: instant
            except Exception as e:
                err = e
            kind = self.classify(err)
            out = self.outcomes
            with self._lock:
                setattr(out, kind, getattr(out, kind) + 1)
                if kind == "accepted":
                    out.latencies_ms.append((t1 - t0) * 1e3)
                    if trace_id is not None:
                        self.trace_ids.append(trace_id)
            self._outstanding.release()

    def _submitter(self, idx: int) -> None:
        from ray_tpu.core.exceptions import BackPressureError

        out = self.outcomes
        interval = self.threads / self.rps
        next_t = time.perf_counter() + self.rng.random() * interval
        i = 0
        while not self._stop.is_set():
            now = time.perf_counter()
            if now < next_t:
                time.sleep(min(interval, next_t - now))
                continue
            next_t += interval
            i += 1
            with self._lock:
                out.submitted += 1
            t0 = time.perf_counter()
            tctx = (_tracing.new_id(), "") if self.trace else None
            try:
                with _tracing.ctx_scope(tctx):
                    ref = self.handle.remote(
                        self.payload_fn(idx, i),
                        _timeout_s=self.request_timeout_s)
            except BackPressureError:
                with self._lock:
                    out.shed += 1
                self._outstanding.release()
                continue
            except Exception:
                with self._lock:
                    out.other_error += 1
                self._outstanding.release()
                continue
            self._worker.add_done_callback(
                ref, lambda r=ref, t=t0,
                tid=(tctx[0] if tctx else None): self._done_q.put(
                    (r, t, time.perf_counter(), tid)))

    def start(self) -> "LoadGenerator":
        self._collector_t = threading.Thread(target=self._collector,
                                             daemon=True)
        self._collector_t.start()
        self._threads = [
            threading.Thread(target=self._submitter, args=(k,), daemon=True)
            for k in range(self.threads)]
        self._t_start = time.perf_counter()
        for t in self._threads:
            t.start()
        return self

    def stop_and_drain(self) -> _Outcomes:
        """Stop offering load, then wait until every submitted request
        resolves (deadline + grace); stragglers count as `hung`."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        self.elapsed_s = time.perf_counter() - self._t_start
        deadline = time.monotonic() + self.request_timeout_s \
            + self.resolve_grace_s
        with self._lock:
            submitted = self.outcomes.submitted
        resolved = 0
        while resolved < submitted and time.monotonic() < deadline:
            if self._outstanding.acquire(timeout=0.25):
                resolved += 1
        self._done_q.put(None)
        self._collector_t.join(timeout=10)
        with self._lock:
            self.outcomes.hung = submitted - resolved
        return self.outcomes

    def run(self, duration_s: float) -> _Outcomes:
        self.start()
        time.sleep(duration_s)
        return self.stop_and_drain()


def run_storm(profile: Optional[StormProfile] = None,
              out_path: Optional[str] = DEFAULT_ARTIFACT) -> Dict[str, Any]:
    """Run one storm against a fresh deployment on the CURRENT cluster
    (caller has already ray_tpu.init'd). Returns the result dict (also
    written to `out_path` unless None). Raises nothing on a dirty storm —
    the caller asserts on `result["requests"]["hung"]` etc."""
    from ray_tpu.core import rpc as _rpc
    from ray_tpu.core.config import get_config
    from ray_tpu.serve.config import get_serve_config

    p = profile or StormProfile()
    rng = random.Random(p.seed)
    cfg = get_serve_config()
    saved = {k: getattr(cfg, k) for k in
             ("max_queue_per_replica", "request_retry_budget")}
    cfg.max_queue_per_replica = p.max_queue_per_replica
    cfg.request_retry_budget = p.retry_budget
    core_cfg = get_config()
    saved_traces = core_cfg.tracing_max_traces
    if _tracing.enabled():
        # one trace per submitted request: a quick storm roots a few
        # thousand, which brushes the default per-trace eviction cap —
        # evicting a live trace would read as a broken chain
        core_cfg.tracing_max_traces = max(saved_traces, 50_000)
    injector = (_rpc.install_fault_injector(p.fault_spec, p.seed)
                if p.fault_spec else None)
    try:
        return _run_storm_inner(p, rng, injector, out_path)
    finally:
        # an aborted storm must not leave the process dropping 2% of every
        # replica call (or storm-sized caps) for whatever runs next
        if injector is not None:
            _rpc.clear_fault_injector()
        for k, v in saved.items():
            setattr(cfg, k, v)
        core_cfg.tracing_max_traces = saved_traces


def _collect_trace_report(trace_ids: List[str],
                          out_path: Optional[str]) -> Dict[str, Any]:
    """Post-drain tracing verdict: pull the fleet's spans + clock offsets
    from the GCS, validate every accepted request's chain (parent links
    resolve, >=3 distinct processes), and write the merged chrome trace
    next to the artifact."""
    from ray_tpu.core.api import _global_worker
    from ray_tpu.core.config import get_config
    from ray_tpu.util import timeline

    w = _global_worker()
    # flush our own spans, then poll: worker processes ship theirs on the
    # report-interval cadence, so keep re-pulling until the chain census
    # stops improving (stragglers can be a couple of intervals behind)
    interval_s = max(0.5, get_config().task_events_report_interval_ms / 1e3)
    deadline = time.monotonic() + max(8.0, 6 * interval_s)
    spans, offsets, chains = [], {}, {}
    complete: List[str] = []
    cross3: List[str] = []
    while True:
        w.task_events.flush()
        spans = w.gcs.call("get_profile_events", {}, timeout=30)
        offsets = w.gcs.call("get_span_offsets", {}, timeout=10)
        chains = timeline.validate_chains(spans, trace_ids)
        complete = [t for t, c in chains.items() if c["complete"]]
        cross3 = [t for t in complete if chains[t]["processes"] >= 3]
        if len(cross3) >= len(trace_ids) or time.monotonic() > deadline:
            break
        time.sleep(interval_s)
    doc = timeline.merge_chrome(spans, offsets)
    problems = timeline.validate_chrome(doc)
    chrome_path = None
    if out_path:
        chrome_path = out_path + ".trace.json"
        with open(chrome_path, "w") as f:
            json.dump(doc, f)
    incomplete_sample = [
        {"trace_id": t, **{k: v for k, v in chains[t].items()
                           if k != "missing_parents"},
         "missing_parents": chains[t]["missing_parents"][:3]}
        for t in trace_ids if not chains[t]["complete"]][:5]
    return {
        "enabled": True,
        "accepted_traced": len(trace_ids),
        "complete_chains": len(complete),
        "complete_fraction": round(
            len(complete) / max(1, len(trace_ids)), 4),
        "chains_3plus_processes": len(cross3),
        "cross3_fraction": round(len(cross3) / max(1, len(trace_ids)), 4),
        "incomplete_sample": incomplete_sample,
        "chrome_events": len(doc["traceEvents"]),
        "chrome_valid": not problems,
        "chrome_problems": problems[:5],
        "chrome_path": chrome_path,
        "clock_sources": len(offsets),
        "max_abs_clock_offset_us": round(
            max((abs(v) for v in offsets.values()), default=0.0), 1),
    }


def _run_storm_inner(p: StormProfile, rng: random.Random, injector,
                     out_path: Optional[str]) -> Dict[str, Any]:
    import ray_tpu
    from ray_tpu import serve

    service_time_s = p.service_time_s
    traced = _tracing.enabled()

    @ray_tpu.remote
    def _nested_echo(i):
        return i

    @serve.deployment(
        name="storm_target",
        num_replicas=p.num_replicas,
        max_concurrent_queries=p.replica_concurrency,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=p.num_replicas, max_replicas=p.max_replicas,
            target_num_ongoing_requests_per_replica=p.replica_concurrency,
            upscale_delay_s=1.0, downscale_delay_s=30.0),
    )
    class StormTarget:
        def __call__(self, i):
            if traced:
                # nested task: the replica's execution span becomes the
                # parent of a submit->lease->dispatch->execute chain in a
                # THIRD process (a pool worker), so every accepted
                # request's trace crosses driver -> replica -> worker
                ray_tpu.get(_nested_echo.remote(i), timeout=30)
            time.sleep(service_time_s)
            return i

    handle = serve.run(StormTarget.bind(), name="storm")
    # warm: every replica answered once before the clock starts
    ray_tpu.get([handle.remote(i) for i in range(p.num_replicas * 2)],
                timeout=60)
    serve.reset_router_stats()

    stop = threading.Event()
    kills = 0

    gen = LoadGenerator(handle, rps=p.offered_rps,
                        request_timeout_s=p.request_timeout_s,
                        threads=p.submitter_threads, rng=rng,
                        resolve_grace_s=p.resolve_grace_s, trace=traced)

    def killer() -> None:
        # victims come from the HANDLE's push-refreshed replica set (local,
        # no controller RPC: under a storm the controller's exec slots are
        # busy autoscaling/health-checking and an RPC here can starve)
        nonlocal kills
        while not stop.wait(p.kill_period_s):
            try:
                with handle._lock:
                    replicas = list(handle._replicas)
                if len(replicas) < 2:
                    continue  # never kill the last replica
                victim = replicas[rng.randrange(len(replicas))]
                ray_tpu.kill(victim)
                kills += 1
                logger.info("storm killed replica %s", victim)
            except Exception:
                logger.warning("storm kill pass failed", exc_info=True)

    # kill_period_s <= 0 disables the kill loop entirely (the traced storm
    # runs kill-free: a hard-killed replica takes its unflushed spans with
    # it, which would charge span loss against chain completeness). The
    # guard matters — stop.wait(0) returns immediately, so an unguarded
    # thread would busy-kill replicas back to back.
    kill_t = None
    if p.kill_period_s > 0:
        kill_t = threading.Thread(target=killer, daemon=True)
        kill_t.start()
    gen.start()
    time.sleep(p.duration_s)
    stop.set()
    # Every submitted request must RESOLVE (result / typed timeout / typed
    # shed) within deadline + grace; anything left is a hung request.
    out = gen.stop_and_drain()
    if kill_t is not None:
        kill_t.join(timeout=p.kill_period_s + 10)
    elapsed = gen.elapsed_s
    tracing_blk = (_collect_trace_report(gen.trace_ids, out_path)
                   if traced else None)

    stats = serve.router_stats()
    lat = sorted(out.latencies_ms)
    result: Dict[str, Any] = {
        "bench": "serve_storm",
        "round": 9,
        "seed": p.seed,
        "fault_spec": p.fault_spec,
        "fault_stats": dict(injector.stats) if injector else {},
        "duration_s": round(elapsed, 2),
        "capacity_rps_est": round(p.capacity_rps, 1),
        "offered_rps": round(p.offered_rps, 1),
        "overload_x": p.overload,
        "request_timeout_s": p.request_timeout_s,
        "replicas": {"min": p.num_replicas, "max": p.max_replicas,
                     "concurrency": p.replica_concurrency,
                     "kills": kills},
        "requests": {
            "submitted": out.submitted,
            "accepted": out.accepted,
            "shed": out.shed,
            "timeout": out.timeout,
            "replica_death": out.replica_death,
            "other_error": out.other_error,
            "hung": out.hung,
        },
        "router": stats,
        "latency_ms": {
            "p50_accepted": round(_percentile(lat, 0.50) or 0.0, 2),
            "p99_accepted": round(_percentile(lat, 0.99) or 0.0, 2),
        },
        "zero_hung": out.hung == 0,
    }
    if tracing_blk is not None:
        result["tracing"] = tracing_blk
    serve.delete("storm_target")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
    return result


class HeadKiller:
    """Mid-storm head kill-and-promote (`--kill-head`): snapshots the
    active head, starts a warm StandbyHead, crash-stops the head (no lease
    relinquish — the HARD failure: promotion waits out the TTL), adopts the
    promoted head and drives a probe actor through it so the tracked
    promotion latency (lease-expiry -> first-scheduled-task) has a far
    edge even on an otherwise idle control plane."""

    def __init__(self, cluster, kill_after_s: float, lease_ttl_s: float):
        self.cluster = cluster
        self.kill_after_s = kill_after_s
        self.lease_ttl_s = lease_ttl_s
        self.record: Dict[str, Any] = {}
        self._cancel = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="storm-head-killer", daemon=True)

    def start(self) -> "HeadKiller":
        self._thread.start()
        return self

    def join(self, timeout: float) -> None:
        self._cancel.set()
        self._thread.join(timeout)

    def _run(self) -> None:
        import ray_tpu

        if self._cancel.wait(self.kill_after_s):
            return
        rec = self.record
        try:
            self.cluster.gcs._write_snapshot()
        except Exception:
            logger.exception("pre-kill snapshot failed; standby promotes "
                             "from the periodic loop's last write")
        standby = self.cluster.start_standby()
        time.sleep(max(0.3, self.lease_ttl_s / 2))  # one standby tail poll
        rec["epoch_before"] = self.cluster.gcs.fence_epoch
        rec["killed_at"] = time.time()
        logger.warning("storm killing the ACTIVE HEAD (epoch %d)",
                       rec["epoch_before"])
        self.cluster.gcs.kill()
        try:
            rec["new_address"] = self.cluster.adopt_promoted(
                standby, timeout=self.lease_ttl_s * 10 + 30)
        except Exception as e:
            rec["error"] = f"promotion failed: {e}"
            logger.exception("standby promotion failed")
            return
        rec["epoch_after"] = self.cluster.gcs.fence_epoch

        @ray_tpu.remote
        class _PromotionProbe:
            def ping(self):
                return 1

        try:
            probe = _PromotionProbe.options(num_cpus=0).remote()
            ray_tpu.get(probe.ping.remote(), timeout=60)
            ray_tpu.kill(probe)
        except Exception as e:
            rec["probe_error"] = str(e)
        rec["promotion"] = dict(self.cluster.gcs.promotion or {})
        lat = rec["promotion"].get("latency_s")
        logger.warning("head promoted: epoch %d -> %d at %s, "
                       "lease-expiry->first-scheduled-task %.3fs",
                       rec["epoch_before"], rec["epoch_after"],
                       rec["new_address"], lat if lat is not None else -1.0)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    import ray_tpu

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--overload", type=float, default=4.0)
    ap.add_argument("--seed", type=int, default=None,
                    help="fault-injection + kill-choice seed (default: "
                         "RAY_TPU_FAULT_INJECTION_SEED or 0)")
    ap.add_argument("--quick", action="store_true",
                    help="short CI profile (~6 s; ~10 s with --kill-head)")
    ap.add_argument("--json", default=DEFAULT_ARTIFACT,
                    help=f"artifact path (default {DEFAULT_ARTIFACT})")
    ap.add_argument("--traced", action="store_true",
                    help="run with distributed tracing enabled: every "
                         "request roots a trace, the merged chrome "
                         "timeline lands next to the artifact, and the "
                         "run fails unless >=99%% of accepted requests "
                         "have complete cross-process span chains. "
                         "Disables the replica kill loop (a hard-killed "
                         "replica loses its unflushed spans); the fault "
                         "injector still drops submissions, so failover "
                         "retries stay in the traces")
    ap.add_argument("--kill-period", type=float, default=None,
                    help="override the replica kill period in seconds; 0 "
                         "disables the kill loop (CI's tracing stage uses "
                         "this for an untraced kill-free baseline "
                         "comparable to --traced)")
    ap.add_argument("--kill-head", action="store_true",
                    help="kill-and-promote the GCS head mid-storm: a warm "
                         "standby takes over via the lease/fencing-epoch "
                         "CAS; asserts zero hung requests, bounded "
                         "promotion latency and no typed-error spike "
                         "beyond the shed baseline")
    ap.add_argument("--headfail-json", default=HEADFAIL_ARTIFACT,
                    help="promotion-latency artifact for --kill-head "
                         f"(default {HEADFAIL_ARTIFACT})")
    ap.add_argument("--promotion-budget", type=float, default=None,
                    help="max allowed lease-expiry -> first-scheduled-task "
                         "latency in seconds (--kill-head); default is "
                         "machine-calibrated from effective CPU count "
                         f"({PROMOTION_BUDGET_S}s at >= "
                         f"{_ERROR_SPIKE_FULL_CPUS} cpus, relaxed toward "
                         f"{_PROMOTION_BUDGET_1CPU_S}s at 1)")
    ap.add_argument("--lease-ttl", type=float, default=1.0,
                    help="head lease TTL for the --kill-head run")
    args = ap.parse_args(argv)

    import os

    seed = (args.seed if args.seed is not None
            else int(os.environ.get("RAY_TPU_FAULT_INJECTION_SEED", "0")))
    kw: Dict[str, Any] = dict(seed=seed, overload=args.overload,
                              duration_s=args.duration)
    if args.quick:
        kw.update(KILLHEAD_QUICK_PROFILE if args.kill_head
                  else QUICK_PROFILE)
    if args.kill_period is not None:
        kw["kill_period_s"] = args.kill_period
    if args.traced:
        from ray_tpu.core.config import get_config

        # env AND the live config: worker subprocesses (replicas, pool
        # workers) build their config from the inherited environment, so
        # flipping only the driver's loaded config would leave every other
        # process untraced and the chains single-process
        os.environ["RAY_TPU_TRACING_ENABLED"] = "1"
        get_config().tracing_enabled = True
        kw["kill_period_s"] = 0.0  # hard-killed replicas lose their spans
    profile = StormProfile(**kw)

    cluster = None
    killer = None
    if args.kill_head:
        from ray_tpu.core.cluster import Cluster
        from ray_tpu.core.config import get_config

        get_config().head_lease_ttl_s = args.lease_ttl
        cluster = Cluster(
            snapshot_uri=f"memory://storm-head-{os.getpid()}")
        cluster.add_node(resources={
            "CPU": float(max(8, profile.max_replicas + 2)), "TPU": 8.0})
        cluster.connect()
        killer = HeadKiller(cluster, kill_after_s=profile.duration_s * 0.4,
                            lease_ttl_s=args.lease_ttl).start()
    else:
        ray_tpu.init(num_cpus=max(8, profile.max_replicas + 2),
                     resources={"TPU": 8})
    try:
        result = run_storm(profile, out_path=args.json)
        if killer is not None:
            killer.join(timeout=args.lease_ttl * 10 + 90)
    finally:
        try:
            from ray_tpu import serve

            serve.shutdown()
        finally:
            ray_tpu.shutdown()
            if cluster is not None:
                cluster.shutdown()

    req = result["requests"]
    print(f"serve storm: seed={result['seed']} "
          f"offered={result['offered_rps']}rps "
          f"(~{result['overload_x']}x capacity "
          f"{result['capacity_rps_est']}rps) for {result['duration_s']}s, "
          f"kills={result['replicas']['kills']}")
    print(f"  submitted={req['submitted']} accepted={req['accepted']} "
          f"shed={req['shed']} timeout={req['timeout']} "
          f"replica_death={req['replica_death']} "
          f"other={req['other_error']} hung={req['hung']}")
    print(f"  router retries={result['router']['retries']} "
          f"failovers={result['router']['failovers']} "
          f"p50_accepted={result['latency_ms']['p50_accepted']}ms "
          f"p99_accepted={result['latency_ms']['p99_accepted']}ms")
    if args.json:
        print(f"  artifact: {args.json}")
    failed = False
    if req["hung"] or not result["zero_hung"]:
        print(f"STORM FAILED: {req['hung']} hung request(s) "
              f"(seed {result['seed']})")
        failed = True
    if args.traced:
        tr = result.get("tracing") or {}
        print(f"  tracing: {tr.get('complete_chains')}/"
              f"{tr.get('accepted_traced')} complete chains "
              f"({tr.get('cross3_fraction', 0):.1%} across >=3 processes), "
              f"{tr.get('chrome_events')} events -> {tr.get('chrome_path')} "
              f"(valid={tr.get('chrome_valid')}), "
              f"max clock offset "
              f"{tr.get('max_abs_clock_offset_us', 0) / 1e3:.2f}ms "
              f"over {tr.get('clock_sources')} sources")
        if tr.get("cross3_fraction", 0.0) < 0.99:
            print(f"STORM FAILED: only {tr.get('cross3_fraction', 0):.1%} "
                  f"of accepted requests have complete >=3-process span "
                  f"chains (need 99%); sample: "
                  f"{tr.get('incomplete_sample')}")
            failed = True
        if not tr.get("chrome_valid"):
            print(f"STORM FAILED: merged chrome trace invalid: "
                  f"{tr.get('chrome_problems')}")
            failed = True
    if args.kill_head:
        failed |= _report_head_kill(killer.record, result, args)
    if failed:
        return 1
    print("storm clean: every request resolved within its deadline")
    return 0


# Typed errors that are NOT overload responses (shed/timeout are the serve
# plane doing its job at 4x load): a head failover must not spike these
# beyond a small fraction of traffic. Baseline for the --kill-head quick
# profile this check runs under (HEADFAIL_r11): replica_death+other ~= 6%
# of submitted. (The full SERVESTORM_r09 profile runs longer with more
# replica kills and sits near 35% — it is not the baseline here.)
ERROR_SPIKE_MAX_FRACTION = 0.10

# The 10% bound was calibrated on multi-core hardware, where a killed
# replica's replacement boots while the storm's load loop keeps running on
# other cores. On a starved 1-2 CPU box the respawn path CONTENDS with the
# load generator, so the death window stretches and replica_death errors
# pile up with no control-plane regression at all: a pristine-tree control
# run on a 1-CPU host measures ~42% (vs ~6% on real hardware). Scale the
# bound by detected parallelism — full strictness at >= 8 CPUs, linearly
# relaxed toward 60% at 1 CPU — so the stage stays meaningful on real
# hardware without flaking on constrained CI boxes.
_ERROR_SPIKE_FULL_CPUS = 8
_ERROR_SPIKE_1CPU_MAX = 0.60


def _effective_cpus() -> int:
    """EFFECTIVE parallelism, not host core count: a cgroup/affinity-
    limited CI runner on a big host is exactly the starved case the
    calibration exists for."""
    try:
        return len(_os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return _os.cpu_count() or 1


def error_spike_bound() -> float:
    cpus = _effective_cpus()
    if cpus >= _ERROR_SPIKE_FULL_CPUS:
        return ERROR_SPIKE_MAX_FRACTION
    frac = (_ERROR_SPIKE_FULL_CPUS - cpus) / (_ERROR_SPIKE_FULL_CPUS - 1)
    return round(ERROR_SPIKE_MAX_FRACTION
                 + (_ERROR_SPIKE_1CPU_MAX - ERROR_SPIKE_MAX_FRACTION) * frac,
                 4)


# The 1.0s promotion budget has the same hardware assumption as the error
# spike bound: the standby's lease CAS + snapshot restore + raylet
# re-adoption race the load generator for cores. On a 1-CPU box the whole
# promotion pipeline timeshares with request traffic, so the same healthy
# control plane measures several times the multi-core latency. Calibrate
# identically: full strictness at >= _ERROR_SPIKE_FULL_CPUS, linearly
# relaxed toward _PROMOTION_BUDGET_1CPU_S at 1 CPU. An explicit
# --promotion-budget always wins.
PROMOTION_BUDGET_S = 1.0
_PROMOTION_BUDGET_1CPU_S = 4.0


def promotion_budget_bound() -> float:
    cpus = _effective_cpus()
    if cpus >= _ERROR_SPIKE_FULL_CPUS:
        return PROMOTION_BUDGET_S
    frac = (_ERROR_SPIKE_FULL_CPUS - cpus) / (_ERROR_SPIKE_FULL_CPUS - 1)
    return round(PROMOTION_BUDGET_S
                 + (_PROMOTION_BUDGET_1CPU_S - PROMOTION_BUDGET_S) * frac, 3)


def _report_head_kill(rec: Dict[str, Any], result: Dict[str, Any],
                      args) -> bool:
    """Print + persist the kill-head verdict (HEADFAIL artifact). Returns
    True when the run FAILED (no promotion, promotion over budget, or a
    typed-error spike beyond the shed baseline)."""
    from ray_tpu.envelope import bench_broadcast_1k

    failed = False
    promotion = rec.get("promotion") or {}
    latency = promotion.get("latency_s")
    req = result["requests"]
    errs = req["replica_death"] + req["other_error"]
    err_frac = errs / max(1, req["submitted"])
    bound = error_spike_bound()
    promo_budget = (args.promotion_budget
                    if args.promotion_budget is not None
                    else promotion_budget_bound())
    print(f"  head kill: epochs {rec.get('epoch_before')} -> "
          f"{rec.get('epoch_after')} new_head={rec.get('new_address')} "
          f"lease_ttl={args.lease_ttl}s")
    if rec.get("error") or latency is None:
        print(f"HEADFAIL: standby never promoted / never scheduled "
              f"({rec.get('error') or rec.get('probe_error')})")
        failed = True
    else:
        print(f"  promotion latency (lease-expiry -> first-scheduled-task): "
              f"{latency:.3f}s (budget {promo_budget}s at "
              f"{_effective_cpus()} effective cpus, tailed "
              f"snapshot v{promotion.get('tailed_version')})")
        if latency > promo_budget:
            print(f"HEADFAIL: promotion latency {latency:.3f}s over the "
                  f"{promo_budget}s budget")
            failed = True
    print(f"  typed-error spike check: replica_death+other = {errs} "
          f"({err_frac:.1%} of submitted, max {bound:.0%} at "
          f"{_effective_cpus()} effective cpus "
          f"[{ERROR_SPIKE_MAX_FRACTION:.0%} on >= "
          f"{_ERROR_SPIKE_FULL_CPUS}]; shed baseline {req['shed']} "
          f"+ timeout {req['timeout']})")
    if err_frac > bound:
        print("HEADFAIL: typed-error spike beyond the shed baseline")
        failed = True

    artifact = {
        "bench": "head_failover_storm",
        "round": 11,
        "seed": result["seed"],
        "lease_ttl_s": args.lease_ttl,
        "promotion_budget_s": promo_budget,
        "epochs": {"before": rec.get("epoch_before"),
                   "after": rec.get("epoch_after")},
        "promotion": promotion,
        "promotion_latency_s": latency,
        "new_head_address": rec.get("new_address"),
        "storm": {
            "duration_s": result["duration_s"],
            "offered_rps": result["offered_rps"],
            "requests": dict(req),
            "zero_hung": result["zero_hung"],
            "error_spike_fraction": round(err_frac, 4),
            "error_spike_max_fraction": bound,
            "error_spike_base_fraction": ERROR_SPIKE_MAX_FRACTION,
            "error_spike_cpus": _effective_cpus(),
            "replica_kills": result["replicas"]["kills"],
        },
        "broadcast_1k_nodes": bench_broadcast_1k(),
        "passed": not failed,
    }
    if args.headfail_json:
        with open(args.headfail_json, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
        print(f"  headfail artifact: {args.headfail_json}")
    return failed


if __name__ == "__main__":
    import sys

    sys.exit(main())
