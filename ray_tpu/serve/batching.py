"""`@serve.batch`: opportunistic dynamic request batching.

Reference: `python/ray/serve/batching.py:206` — individual calls to the
decorated method queue up; one underlying invocation receives the whole
batch (a list) and returns a list of per-call results. Batches close when
`max_batch_size` requests are waiting or the oldest has waited
`batch_wait_timeout_s`.

The reference implementation is asyncio-based (its replicas run an event
loop); replicas here execute calls on threads (max_concurrency > 1), so
the batcher is a condition-variable queue: callers block on their own
event, one caller per batch is elected leader and runs the underlying
function for everyone. This is exactly the hand-off continuous-batching
LLM engines use between request threads and the model loop
(`serve/llm_engine.py`), generalized to any callable.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu.core.exceptions import RequestTimeoutError

# Per-thread serve-request context: the replica's handle_request pushes the
# request's end-to-end deadline before dispatching into user code, so a
# @serve.batch waiter knows its own deadline without threading it through
# user signatures. Stack-disciplined (push returns the previous value) so
# nested deployment calls within one thread restore correctly.
_request_ctx = threading.local()


def push_request_deadline(deadline_ts: Optional[float]) -> Optional[float]:
    prev = getattr(_request_ctx, "deadline_ts", None)
    _request_ctx.deadline_ts = deadline_ts
    return prev


def pop_request_deadline(prev: Optional[float]) -> None:
    _request_ctx.deadline_ts = prev


def current_request_deadline() -> Optional[float]:
    """Wall-clock deadline of the serve request on this thread (None
    outside a deadline-carrying request)."""
    return getattr(_request_ctx, "deadline_ts", None)


class _Waiter:
    __slots__ = ("arg", "event", "result", "error", "deadline_ts")

    def __init__(self, arg):
        self.arg = arg
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.deadline_ts = current_request_deadline()


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._cv = threading.Condition()
        self._lock = self._cv  # one lock: queue state + leader election
        self._queue: List[_Waiter] = []
        self._leader_running = False

    def submit(self, self_arg, arg):
        w = _Waiter(arg)
        lead = False
        with self._lock:
            self._queue.append(w)
            if len(self._queue) >= self.max_batch_size:
                self._cv.notify_all()  # wake the leader: batch is full
            if not self._leader_running:
                self._leader_running = True
                lead = True
        if lead:
            self._lead(self_arg)
        w.event.wait()
        if w.error is not None:
            raise w.error
        return w.result

    def _lead(self, self_arg) -> None:
        """The elected leader waits for the batch window, drains the queue,
        runs the underlying fn once, and distributes results."""
        deadline = time.monotonic() + self.timeout
        with self._cv:
            while (len(self._queue) < self.max_batch_size
                   and (remaining := deadline - time.monotonic()) > 0):
                self._cv.wait(timeout=remaining)
        with self._lock:
            batch, self._queue = (self._queue[:self.max_batch_size],
                                  self._queue[self.max_batch_size:])
            if self._queue:
                # late arrivals get their own leader: hand off before
                # running so the next window opens immediately
                threading.Thread(target=self._relead, args=(self_arg,),
                                 daemon=True).start()
            else:
                self._leader_running = False
        # Drop waiters whose end-to-end deadline expired while queued for
        # the batch window: they get the typed error immediately and the
        # underlying invocation is spent only on requests a caller is
        # still waiting for (the same pre-dequeue discipline the replica
        # applies before dispatch).
        now = time.time()
        expired = [w for w in batch
                   if w.deadline_ts is not None and now >= w.deadline_ts]
        if expired:
            batch = [w for w in batch if w not in expired]
            for w in expired:
                w.error = RequestTimeoutError(
                    "request expired in batch queue before the batch ran")
                w.event.set()
            if not batch:
                return
        try:
            args = [w.arg for w in batch]
            results = (self.fn(self_arg, args) if self_arg is not _NO_SELF
                       else self.fn(args))
            if len(results) != len(batch):
                raise ValueError(
                    f"batched function returned {len(results)} results "
                    f"for a batch of {len(batch)}")
            for w, r in zip(batch, results):
                w.result = r
        except BaseException as e:
            for w in batch:
                w.error = e
        finally:
            for w in batch:
                w.event.set()

    def _relead(self, self_arg) -> None:
        with self._lock:
            if not self._queue:
                self._leader_running = False
                return
        self._lead(self_arg)


_NO_SELF = object()


def batch(_func=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a function/method taking a LIST of requests and returning a
    LIST of results; callers invoke it with single requests (reference
    `serve.batch`). Works on plain functions and on methods (per-instance
    batch queues)."""

    def wrap(fn):
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def method_wrapper(self, arg):
            b = getattr(self, attr, None)
            if b is None:
                b = _Batcher(fn, max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, b)
            return b.submit(self, arg)

        shared = _Batcher(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        def func_wrapper(arg):
            return shared.submit(_NO_SELF, arg)

        import inspect

        params = list(inspect.signature(fn).parameters)
        is_method = params and params[0] == "self"
        out = method_wrapper if is_method else func_wrapper
        out._serve_batch_config = {  # type: ignore[attr-defined]
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return out

    return wrap(_func) if _func is not None else wrap
