"""Inference: KV-cache prefill/decode and a jitted generate loop.

The serving-side compute path (used by Serve model replicas — the
reference delegates this to torch; here it is native): prefill builds the
stacked per-layer KV cache in one pass, decode steps are single-token
forward passes attending over the cache (static max_len shapes, masked by
position, so the whole generate loop is one compiled `lax.scan`).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import (ModelConfig, _deq_tree,
                                        _embed_lookup, lm_head_weights)
from ray_tpu.ops.layers import apply_rotary, rms_norm, rotary_embedding, swiglu


def _project_qkv(cfg: ModelConfig, p, x, cos, sin):
    b, s, _ = x.shape
    hd = cfg.head_dim
    q = (x @ p["wq"]).reshape(b, s, cfg.n_heads, hd)
    k = (x @ p["wk"]).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ p["wv"]).reshape(b, s, cfg.n_kv_heads, hd)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    return q, k, v


def _mlp(cfg: ModelConfig, p, h):
    if cfg.n_experts > 0:
        from ray_tpu.ops.moe import moe_ffn

        out, _ = moe_ffn(h, p["router"], p["w_gate"], p["w_up"], p["w_down"],
                         cfg.capacity_factor)
        return out
    return swiglu(h @ p["w_gate"], h @ p["w_up"]) @ p["w_down"]


def _gqa_decode_attention(q, k_cache, v_cache, k_cur, v_cur, mask):
    """Single-token grouped-query attention over a cache window plus the
    current token's (not-yet-written) K/V row.

    q [b,h,1,hd]; k_cache/v_cache [b,kvh,Lw,hd] (a prefix window of the
    slot cache); k_cur/v_cur [b,kvh,hd]; mask [b,Lw] with True = attend
    (STRICT: the current position is not in the cache — it contributes via
    the separate k_cur/v_cur term). Unlike `_masked_attention` this never
    materializes GQA-repeated K/V (those copies are cache-sized, per layer,
    per step): queries are grouped [b,kvh,rep,hd] and contracted against
    the shared K/V heads directly.
    """
    b, h, _, hd = q.shape
    kvh = k_cache.shape[1]
    qg = q[:, :, 0].reshape(b, kvh, h // kvh, hd)
    scale = hd ** -0.5
    lg = jnp.einsum("bgrd,bgld->bgrl", qg, k_cache).astype(jnp.float32) * scale
    lg = jnp.where(mask[:, None, None, :], lg, -1e30)
    self_lg = jnp.einsum("bgrd,bgd->bgr", qg, k_cur).astype(jnp.float32) * scale
    lg = jnp.concatenate([lg, self_lg[..., None]], axis=-1)
    probs = jax.nn.softmax(lg, axis=-1).astype(q.dtype)
    win = k_cache.shape[2]
    attn = jnp.einsum("bgrl,bgld->bgrd", probs[..., :win], v_cache) \
        + probs[..., win:] * v_cur[:, :, None]
    return attn.reshape(b, h, hd)


def _masked_attention(q, k, v, mask):
    """q [b,h,sq,hd] over cached k/v [b,kvh,L,hd] with bool mask [sq,L]."""
    n_rep = q.shape[1] // k.shape[1]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=1)
        v = jnp.repeat(v, n_rep, axis=1)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def prefill(params: Dict, tokens: jax.Array, cfg: ModelConfig,
            max_len: int, logits_index: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, Dict]:
    """Process the prompt; returns (logits [b, vocab], cache).

    Logits come from the last position, or from `logits_index` [b] when the
    prompt is right-padded (the causal mask keeps positions < index exact).
    cache = {"k": [L,b,kvh,max_len,hd], "v": ..., "length": scalar}.
    """
    b, s = tokens.shape
    hd = cfg.head_dim
    positions = jnp.arange(s)
    cos, sin = rotary_embedding(positions, hd, cfg.rope_theta)
    cos, sin = cos[None], sin[None]
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    causal = jnp.tril(jnp.ones((s, s), bool))
    pad = jnp.zeros((s, max_len - s), bool)
    mask = jnp.concatenate([causal, pad], axis=1)

    def body(x, lp):
        lp = _deq_tree(lp, cfg.dtype)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, h, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        k_cache = jnp.zeros((b, cfg.n_kv_heads, max_len, hd), cfg.dtype)
        v_cache = jnp.zeros((b, cfg.n_kv_heads, max_len, hd), cfg.dtype)
        k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(cfg.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(cfg.dtype), (0, 0, 0, 0))
        attn = _masked_attention(q, k_cache, v_cache, mask)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
        x = x + (attn @ lp["wo"]).astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, lp, h2).astype(x.dtype)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = lm_head_weights(params, cfg)
    if logits_index is None:
        sel = x[:, -1]
    else:
        sel = jnp.take_along_axis(
            x, logits_index[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = (sel @ head.astype(cfg.dtype)).astype(jnp.float32)
    cache = {"k": k_all, "v": v_all, "length": jnp.asarray(s, jnp.int32)}
    return logits, cache


def decode_step(params: Dict, cache: Dict, token: jax.Array,
                cfg: ModelConfig) -> Tuple[jax.Array, Dict]:
    """One token for each batch row; returns (logits [b, vocab], cache)."""
    b = token.shape[0]
    hd = cfg.head_dim
    pos = cache["length"]
    max_len = cache["k"].shape[-2]
    cos, sin = rotary_embedding(pos[None], hd, cfg.rope_theta)
    cos, sin = cos[None], sin[None]
    x = _embed_lookup(params["embed"], token[:, None], cfg.dtype)  # [b,1,d]
    mask = (jnp.arange(max_len) <= pos)[None, :]  # [1, max_len]

    def body(x, inputs):
        lp, k_cache, v_cache = inputs
        lp = _deq_tree(lp, cfg.dtype)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, h, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(cfg.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(cfg.dtype), (0, 0, pos, 0))
        attn = _masked_attention(q, k_cache, v_cache, mask)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, 1, cfg.n_heads * hd)
        x = x + (attn @ lp["wo"]).astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, lp, h2).astype(x.dtype)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = lm_head_weights(params, cfg)
    logits = (x[:, 0] @ head.astype(cfg.dtype)).astype(jnp.float32)
    new_cache = {"k": k_all, "v": v_all, "length": pos + 1}
    return logits, new_cache


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "max_len",
                                             "temperature"))
def generate(params: Dict, prompt: jax.Array, cfg: ModelConfig, *,
             max_new_tokens: int = 32, max_len: int = 512,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Autoregressive generation; returns [b, prompt_len + max_new_tokens].

    temperature 0 = greedy; otherwise categorical sampling with `rng`.
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    logits, cache = prefill(params, prompt, cfg, max_len)

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    first = sample(logits, rng)

    def step(carry, key):
        cache, token = carry
        logits, cache = decode_step(params, cache, token, cfg)
        nxt = sample(logits, key)
        return (cache, nxt), token

    keys = jax.random.split(rng, max_new_tokens)
    # each scan step emits its *input* token, so ys = exactly the
    # max_new_tokens sampled tokens (the final step's sample is unused)
    (_, _last), tokens = jax.lax.scan(step, (cache, first), keys)
    return jnp.concatenate([prompt, tokens.T], axis=1)
