from ray_tpu.models.transformer import (
    ModelConfig,
    init_params,
    param_logical_axes,
    forward,
    loss_fn,
    count_params,
)
