"""8B north-star planning: sharded memory budget + projected step time/MFU
for `ModelConfig.llama3_8b()` on a v5e-64 slice (BASELINE.json north star:
>=40% MFU at 8B on 64 chips).

Everything here is derived, not asserted: parameter/optimizer/gradient bytes
come from `jax.eval_shape` over the real TrainState tree (no weights are
ever materialized), activation bytes follow the dots-remat saved set the b1
bench actually uses, and the throughput projection applies the b1 bench's
MEASURED phase efficiencies (BASELINE.md r04/r05 decomposition) to the 8B
FLOP mix, with ICI collective time modeled from the fsdp/tp sharding's
all-gather/reduce-scatter volume at v5e link bandwidth.

Evidence artifact: tests/test_eightb_plan.py writes EIGHTB_PLAN.json from
this module and asserts the budget fits; __graft_entry__.dryrun_multichip
executes a real-width (d_model/d_ff/heads) scaled-layer step on the same
fsdp×tp sharding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

GIB = 1 << 30

# v5e per-chip figures (public spec): 197 TF/s bf16 peak, 16 GiB HBM at
# ~819 GB/s, 4 ICI links x ~45 GB/s/direction.
V5E_PEAK_FLOPS = 197e12
V5E_HBM_BYTES = 16 * GIB
V5E_HBM_BW = 819e9
V5E_ICI_BW = 4 * 45e9

# Measured b1 phase efficiencies (chain-differenced on the real chip,
# BASELINE.md): achieved fraction of ideal time per phase.
B1_EFF = {"forward": 0.93, "backward": 0.65, "optimizer": 0.75}


def _tree_bytes(tree: Any) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(tree))


def eightb_plan(n_chips: int = 64, fsdp: int = 16, tp: int = 4,
                batch_per_chip_tokens: int = 4096,
                seq: int = 4096) -> Dict[str, Any]:
    """Returns the budget + projection dict for llama3_8b on an
    fsdp×tp = n_chips v5e slice. Raises if the sharding doesn't divide."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import ModelConfig, init_params
    from ray_tpu.train.step import default_optimizer

    assert fsdp * tp == n_chips, (fsdp, tp, n_chips)
    cfg = dataclasses.replace(ModelConfig.llama3_8b(), max_seq_len=seq,
                              remat="dots")
    # tp shards heads/mlp; fsdp shards everything ZeRO-3 style. Check the
    # tp-sharded dims divide (vocab 128256 = 128-multiple; heads 32; kv 8
    # needs tp <= 8; d_ff 14336 = 4 * 3584).
    for name, dim in (("n_heads", cfg.n_heads), ("n_kv_heads", cfg.n_kv_heads),
                      ("d_ff", cfg.d_ff), ("vocab", cfg.vocab_size)):
        if dim % tp:
            raise ValueError(f"tp={tp} does not divide {name}={dim}")

    optimizer = default_optimizer()
    p_shape = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    o_shape = jax.eval_shape(optimizer.init, p_shape)
    n_params = sum(int(jnp.prod(jnp.array(x.shape)))
                   for x in jax.tree_util.tree_leaves(p_shape))
    param_bytes = _tree_bytes(p_shape)          # bf16 weights
    opt_bytes = _tree_bytes(o_shape)            # fp32 mu/nu (+ scalars)
    grad_bytes = param_bytes                    # grads in param dtype

    shards = fsdp * tp
    per_chip_state = (param_bytes + opt_bytes + grad_bytes) / shards

    # Activations under dots remat, per layer, per chip: the saved set is
    # the dot outputs (qkv 2d, attn out d, attn proj d, gate+up 2*dff,
    # down d) + the scan carry, in bf16, with batch*seq tokens split over
    # fsdp(dp-like data axis) and widths over tp.
    tokens_per_chip = batch_per_chip_tokens      # per-chip token count
    d, dff = cfg.d_model, cfg.d_ff
    saved_per_token = (2 * d      # qkv (q d + kv d/2 each at GQA 8/32... keep 2d upper bound)
                       + d        # attn out
                       + d        # attn proj
                       + 2 * dff  # gate + up
                       + d        # down out
                       + d)       # carry
    act_bytes_layer = tokens_per_chip * saved_per_token * 2 / tp
    act_bytes = act_bytes_layer * cfg.n_layers
    # logits working set with chunked loss (loss_chunk=512): b*chunk*V fp32
    logits_bytes = 512 * cfg.vocab_size * 4 / tp

    headroom = V5E_HBM_BYTES - per_chip_state - act_bytes - logits_bytes

    # ---- throughput projection from measured b1 efficiencies
    attn_flops_tok = 6 * cfg.n_layers * cfg.d_model * seq * 0.5 * 2
    flops_tok = 6 * n_params + attn_flops_tok
    fwd_ideal = flops_tok / 3 / V5E_PEAK_FLOPS       # s/token/chip at peak
    bwd_ideal = 2 * flops_tok / 3 / V5E_PEAK_FLOPS
    # optimizer: HBM-bound full-state sweep per step, amortized per token
    opt_sweep_bytes = (param_bytes * 2 + opt_bytes * 2 + grad_bytes) / shards
    opt_s = opt_sweep_bytes / V5E_HBM_BW / B1_EFF["optimizer"]
    # fsdp collectives per step: all-gather params fwd + bwd, reduce-scatter
    # grads — 3 full param sweeps over ICI per step (ZeRO-3), overlap ~50%
    ici_bytes = 3 * param_bytes / tp
    ici_s = ici_bytes / V5E_ICI_BW * 0.5
    step_compute_s = tokens_per_chip * (
        fwd_ideal / B1_EFF["forward"] + bwd_ideal / B1_EFF["backward"])
    step_s = step_compute_s + opt_s + max(ici_s - 0.3 * step_compute_s, 0)
    tok_s_chip = tokens_per_chip / step_s
    mfu = tok_s_chip * flops_tok / V5E_PEAK_FLOPS

    return {
        "model": "llama3_8b",
        "n_params": int(n_params),
        "slice": f"v5e-{n_chips}",
        "mesh": {"fsdp": fsdp, "tp": tp},
        "per_chip": {
            "hbm_gib": round(V5E_HBM_BYTES / GIB, 2),
            "params_gib": round(param_bytes / shards / GIB, 3),
            "grads_gib": round(grad_bytes / shards / GIB, 3),
            "optimizer_gib": round(opt_bytes / shards / GIB, 3),
            "activations_gib": round(act_bytes / GIB, 3),
            "logits_gib": round(logits_bytes / GIB, 3),
            "headroom_gib": round(headroom / GIB, 3),
        },
        "batch_per_chip_tokens": tokens_per_chip,
        "seq": seq,
        "projection": {
            "basis": "measured b1 phase efficiencies (BASELINE.md) + "
                     "ICI model at v5e link bandwidth",
            "phase_eff": B1_EFF,
            "ici_param_traffic_gib_per_step": round(ici_bytes / GIB, 3),
            "step_s": round(step_s, 4),
            "tokens_per_sec_per_chip": round(tok_s_chip, 1),
            "projected_mfu": round(mfu, 4),
            # the phase model can't see multi-chip effects the single-chip
            # bench never exercised (ICI contention under real traffic,
            # stragglers, host input); 0.75x is the conservative bound we
            # actually claim against the north star
            "conservative_mfu": round(mfu * 0.75, 4),
            "north_star_mfu": 0.40,
            "meets_north_star": bool(mfu * 0.75 >= 0.40),
        },
    }
