"""Flagship model: decoder-only transformer LM (Llama-3 family shapes).

Functional JAX, TPU-first:
  - parameters are a plain pytree with *logical axis* annotations
    (`param_logical_axes`) mapped to mesh axes by `ray_tpu.parallel.AxisRules`
    — dp/fsdp/tp/sp shardings are data, not code;
  - layers are stacked on a leading axis and iterated with `lax.scan`
    (one compiled layer body regardless of depth — fast compiles, and
    `jax.checkpoint` on the body gives per-layer rematerialization);
  - bfloat16 activations/weights with fp32 RMSNorm statistics and fp32
    logits for the softmax-cross-entropy;
  - attention is the pallas flash kernel on TPU; with sequence parallelism
    (mesh sp>1) it switches to ring attention (K/V ppermute rotation) or
    Ulysses (head<->seq all-to-all) over the sp axis per cfg.seq_parallel.

The reference has no model zoo of its own (it delegates to torch; SURVEY
§2.4) — this model is the equivalent of the torch models its Train/RLlib
examples wrap, built natively.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import attention
from ray_tpu.ops.layers import apply_rotary, rms_norm, rotary_embedding, swiglu


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32768
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 8192
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # "none" | "full" | "dots" (selective) | "dots_sans_qkv" (dots minus the
    # q/k/v saves — fits bigger models) | "dots_plus_attn" (dots plus the
    # attention kernel output — no flash-fwd rerun in backward)
    remat: str = "full"
    loss_chunk: int = 0          # >0: chunked cross-entropy (seq chunk size)
    use_ring_attention: bool = False  # set when mesh sp > 1
    # sequence-parallel scheme when sp > 1: "ring" (K/V rotation via
    # ppermute) or "ulysses" (head<->seq all-to-all); "" = dense attention.
    # use_ring_attention=True is kept as an alias for seq_parallel="ring".
    seq_parallel: str = ""
    tie_embeddings: bool = False
    scan_unroll: int = 1         # lax.scan unroll over layers
    # concatenate wq|wk|wv and w_gate|w_up at trace time so each pair of
    # projections is one MXU matmul (params stay separate leaves — the
    # concat is a per-layer 16 MB re-layout XLA schedules off the critical
    # path; the backward then emits one fused dx/dW per group)
    fused_proj: bool = False
    # Mixture of Experts: n_experts > 0 replaces the dense FFN with a
    # top-2-gated MoE (ops/moe.py); experts shard over the "expert" axis.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Fused FFN backward (ops/pallas/fused_ffn.py): the FFN block runs as a
    # custom_vjp with Pallas dW/dx kernels that fuse the swiglu/rmsnorm
    # chains into the matmuls; remat then covers only the attention half
    # (the block saves its own dots-policy-equivalent residuals, and a
    # custom_vjp inside jax.checkpoint would re-run its forward matmuls).
    # Dense-FFN, non-sequence-parallel path only.
    fused_ffn: bool = False
    # Fused attention backward (ops/pallas/fused_attn.py): the attention
    # half runs as a custom_vjp saving post-rotary q/k, v, the flash
    # output and its logsumexp, so the backward skips the rotary/transpose/
    # flash-forward recompute remat would do. Requires fused_ffn (the layer
    # then runs with no jax.checkpoint at all).
    fused_attn: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    # ---- presets ----
    @staticmethod
    def tiny() -> "ModelConfig":
        return ModelConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                           n_kv_heads=2, d_ff=256, max_seq_len=256,
                           dtype=jnp.float32, remat="none")

    @staticmethod
    def b1() -> "ModelConfig":
        """~1.2B params: bench-scale for a single v5e chip."""
        return ModelConfig(vocab_size=32768, d_model=2048, n_layers=16,
                           n_heads=16, n_kv_heads=8, d_ff=8192)

    @staticmethod
    def tiny_moe() -> "ModelConfig":
        return ModelConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                           n_kv_heads=2, d_ff=256, max_seq_len=256,
                           dtype=jnp.float32, remat="none", n_experts=4)

    @staticmethod
    def llama3_8b() -> "ModelConfig":
        """Llama-3-8B shapes (vocab rounded to a 128-multiple sharding unit)."""
        return ModelConfig(vocab_size=128256, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, d_ff=14336,
                           max_seq_len=8192)


# ---------------------------------------------------------------- params


def param_logical_axes(cfg: ModelConfig) -> Dict[str, Any]:
    """Logical axes per parameter leaf (layer-stacked leaves lead with
    'layers', which is never mesh-sharded)."""
    layers: Dict[str, Any] = {
        "attn_norm": ("layers", "embed_nosplit"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "heads"),
        "wv": ("layers", "embed", "heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed_nosplit"),
    }
    if cfg.n_experts > 0:
        layers.update({
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        })
    else:
        layers.update({
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "final_norm": ("embed_nosplit",),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(rng: jax.Array, cfg: ModelConfig) -> Dict[str, Any]:
    """Scaled-normal init; weights stored in cfg.dtype (bf16 master weights
    are avoided — the optimizer keeps fp32 state; see train.step)."""
    k_embed, k_layers, k_head = jax.random.split(rng, 3)
    d, hd = cfg.d_model, cfg.head_dim
    nq, nkv = cfg.n_heads, cfg.n_kv_heads
    L = cfg.n_layers

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 8)
    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, d), cfg.dtype),
        "wq": norm_init(ks[0], (L, d, nq * hd), d),
        "wk": norm_init(ks[1], (L, d, nkv * hd), d),
        "wv": norm_init(ks[2], (L, d, nkv * hd), d),
        "wo": norm_init(ks[3], (L, nq * hd, d), nq * hd),
        "mlp_norm": jnp.ones((L, d), cfg.dtype),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        layers.update({
            "router": (jax.random.normal(ks[7], (L, d, E), jnp.float32)
                       * 0.02).astype(cfg.dtype),
            "w_gate": norm_init(ks[4], (L, E, d, cfg.d_ff), d),
            "w_up": norm_init(ks[5], (L, E, d, cfg.d_ff), d),
            "w_down": norm_init(ks[6], (L, E, cfg.d_ff, d), cfg.d_ff),
        })
    else:
        layers.update({
            "w_gate": norm_init(ks[4], (L, d, cfg.d_ff), d),
            "w_up": norm_init(ks[5], (L, d, cfg.d_ff), d),
            "w_down": norm_init(ks[6], (L, cfg.d_ff, d), cfg.d_ff),
        })
    params: Dict[str, Any] = {
        "embed": (jax.random.normal(k_embed, (cfg.vocab_size, d), jnp.float32)
                  * 0.02).astype(cfg.dtype),
        "final_norm": jnp.ones((d,), cfg.dtype),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = (jax.random.normal(k_head, (d, cfg.vocab_size),
                                               jnp.float32) * 0.02).astype(cfg.dtype)
    return params


def count_params(params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------- forward


def _deq(leaf: Any, dtype) -> Any:
    """Pass arrays through; dequantize `{"int8", "scale"}` leaves produced
    by `models.serving.quantize_model_params` (w8a16 serving: weights live
    in HBM as int8 + per-row fp32 scales; the cast happens on read, inside
    the scan body, so only one layer's bf16 copy is ever transient)."""
    if isinstance(leaf, dict) and "int8" in leaf:
        return (leaf["int8"].astype(jnp.float32) * leaf["scale"]).astype(dtype)
    return leaf


def _deq_tree(p: Dict[str, Any], dtype) -> Dict[str, Any]:
    return {k: _deq(v, dtype) for k, v in p.items()}


def _embed_lookup(emb: Any, tokens: jax.Array, dtype) -> jax.Array:
    """Token-embedding gather; for int8-quantized tables the gather happens
    in int8 (the bf16 [vocab, d] table never materializes)."""
    if isinstance(emb, dict) and "int8" in emb:
        return (emb["int8"][tokens].astype(jnp.float32)
                * emb["scale"][tokens]).astype(dtype)
    return emb[tokens].astype(dtype)


def _attn_half(cfg: ModelConfig, mesh, x, p, cos, sin):
    """Attention sub-block: x + Wo(attn(rotary(qkv(rmsnorm(x)))))."""
    p = _deq_tree(p, cfg.dtype)
    b, s, d = x.shape
    hd = cfg.head_dim

    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    nq_d, nkv_d = cfg.n_heads * hd, cfg.n_kv_heads * hd
    if cfg.fused_proj:
        qkv = h @ jnp.concatenate([p["wq"], p["wk"], p["wv"]], axis=1)
        q, k, v = (qkv[..., :nq_d], qkv[..., nq_d:nq_d + nkv_d],
                   qkv[..., nq_d + nkv_d:])
    else:
        q, k, v = h @ p["wq"], h @ p["wk"], h @ p["wv"]
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)
    q, k, v = (checkpoint_name(t, n) for t, n in
               ((q, "qkv_q"), (k, "qkv_k"), (v, "qkv_v")))
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    sp_scheme = cfg.seq_parallel or ("ring" if cfg.use_ring_attention else "")
    # [b, heads, s, hd]. (A packed [b, s, h*hd] path through
    # ops.attention_packed avoids these transposes, but measured ~1%
    # SLOWER end-to-end at b1 shapes on v5e: the per-head strided block
    # DMA costs more than the dense transposes it removes.)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if sp_scheme == "ring":
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        rep = cfg.n_heads // cfg.n_kv_heads
        if rep > 1:
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        attn = ring_attention_sharded(mesh, q, k, v, causal=True)
    elif sp_scheme == "ulysses":
        # GQA expansion happens inside the kernel, after the all-to-all —
        # KV heads cross ICI unexpanded
        from ray_tpu.ops.ulysses import ulysses_attention_sharded

        attn = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    elif sp_scheme:
        raise ValueError(f"unknown seq_parallel scheme {sp_scheme!r}")
    else:
        attn = attention(q, k, v, causal=True)
    attn = checkpoint_name(
        attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd), "attn_out")
    return x + checkpoint_name((attn @ p["wo"]).astype(x.dtype), "attn_proj")


def _layer(cfg: ModelConfig, mesh, x, layer_params, cos, sin):
    """One transformer block. x: [b, s, d] (s possibly sp-sharded)."""
    x = _attn_half(cfg, mesh, x, layer_params, cos, sin)
    p = _deq_tree(layer_params, cfg.dtype)

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from ray_tpu.ops.moe import moe_ffn

        out, aux = moe_ffn(h, p["router"], p["w_gate"], p["w_up"],
                           p["w_down"], cfg.capacity_factor)
        x = x + out.astype(x.dtype)
        return x, aux
    if cfg.fused_proj:
        gu = h @ jnp.concatenate([p["w_gate"], p["w_up"]], axis=1)
        gate, up = gu[..., :cfg.d_ff], gu[..., cfg.d_ff:]
    else:
        gate, up = h @ p["w_gate"], h @ p["w_up"]
    h = swiglu(checkpoint_name(gate, "ffn_gate"),
               checkpoint_name(up, "ffn_up"))
    x = x + checkpoint_name((h @ p["w_down"]).astype(x.dtype), "ffn_down")
    return x, jnp.zeros((), jnp.float32)


def maybe_remat(layer_fn, cfg: ModelConfig):
    """Wrap a layer body per cfg.remat: "full" recomputes everything in the
    backward pass; "dots" keeps matmul outputs resident and recomputes only
    the cheap elementwise/norm ops — most of full remat's memory win at a
    fraction of its recompute FLOPs; "dots_sans_qkv" additionally drops the
    q/k/v projections from the saved set (recomputing them costs ~2% of a
    step — they're re-derived from the layer input the scan already keeps),
    which is the difference between dots fitting or not for the ~1.2B
    config on one 16G chip."""
    if cfg.remat == "full":
        return jax.checkpoint(layer_fn)
    if cfg.remat == "dots":
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if cfg.remat == "dots_sans_qkv":
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_proj", "ffn_gate", "ffn_up", "ffn_down"))
    if cfg.remat == "dots_plus_attn":
        # dots + the attention kernel output: the backward then never
        # re-runs the flash forward kernel or the rotary/transpose chain —
        # worth ~3% step time for one extra [b, s, d_model] save per layer.
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("attn_out")))
    if cfg.remat != "none":
        raise ValueError(f"unknown remat mode {cfg.remat!r}")
    return layer_fn


def lm_head_weights(params: Dict[str, Any], cfg: ModelConfig) -> jax.Array:
    """[d_model, vocab] output-projection weights in activation dtype."""
    head = (_deq(params["embed"], cfg.dtype).T if cfg.tie_embeddings
            else _deq(params["lm_head"], cfg.dtype))
    return head.astype(cfg.dtype)


def forward_features_with_aux(params: Dict[str, Any], tokens: jax.Array,
                              cfg: ModelConfig,
                              positions: Optional[jax.Array] = None, mesh=None):
    """tokens [b, s] -> (features [b, s, d] after final norm, moe_aux scalar).

    `mesh` is required when any sequence-parallel scheme is active
    (`cfg.seq_parallel` or `cfg.use_ring_attention` — the sp shard_map needs
    it); everything else is pure sharding-annotation-driven SPMD.
    """
    if positions is None:
        positions = jnp.arange(tokens.shape[1])
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)  # gather: [b, s, d]
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta)
    cos, sin = cos[None], sin[None]  # add batch dim

    if cfg.fused_attn and not cfg.fused_ffn:
        raise ValueError("fused_attn requires fused_ffn")
    if cfg.fused_ffn:
        if cfg.n_experts > 0 or cfg.seq_parallel or cfg.use_ring_attention:
            raise ValueError("fused_ffn supports the dense, non-sp path only")
        from ray_tpu.ops.pallas.fused_ffn import ffn_block

        if cfg.fused_attn:
            from ray_tpu.ops.pallas.fused_attn import attn_block

            def attn_fn(x, lp, cos, sin):
                return attn_block(x, lp["attn_norm"], lp["wq"], lp["wk"],
                                  lp["wv"], lp["wo"], cos, sin, cfg.n_heads,
                                  cfg.n_kv_heads, cfg.norm_eps)
        else:
            attn_fn = maybe_remat(functools.partial(_attn_half, cfg, mesh), cfg)

        def body(carry, lp):
            x, aux = carry
            x = attn_fn(x, lp, cos, sin)
            x = ffn_block(x, lp["mlp_norm"], lp["w_gate"], lp["w_up"],
                          lp["w_down"], cfg.norm_eps)
            return (x, aux), None
    else:
        layer_fn = maybe_remat(functools.partial(_layer, cfg, mesh), cfg)

        def body(carry, lp):
            x, aux = carry
            x, layer_aux = layer_fn(x, lp, cos, sin)
            return (x, aux + layer_aux), None

    (x, aux_total), _ = jax.lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), params["layers"],
        unroll=cfg.scan_unroll)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x, aux_total


def forward_with_aux(params: Dict[str, Any], tokens: jax.Array, cfg: ModelConfig,
                     positions: Optional[jax.Array] = None, mesh=None):
    """tokens [b, s] -> (logits [b, s, vocab] fp32, moe_aux_loss scalar)."""
    x, aux_total = forward_features_with_aux(params, tokens, cfg, positions, mesh)
    logits = (x @ lm_head_weights(params, cfg)).astype(jnp.float32)
    return logits, aux_total


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: ModelConfig,
            positions: Optional[jax.Array] = None, mesh=None) -> jax.Array:
    return forward_with_aux(params, tokens, cfg, positions, mesh)[0]


def split_batch(batch: Dict[str, jax.Array]):
    """Normalize a batch to (inputs, targets, mask): accepts pre-shifted
    {"inputs", "targets"} or {"tokens": [b, s+1]}, optional "loss_mask"."""
    if "inputs" in batch:
        return batch["inputs"], batch["targets"], batch.get("loss_mask")
    tokens = batch["tokens"]
    mask = batch.get("loss_mask")
    return tokens[:, :-1], tokens[:, 1:], (None if mask is None else mask[:, 1:])


def token_nll(logits: jax.Array, targets: jax.Array,
              mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token NLL over [..., s, vocab] logits / [..., s] targets,
    masked if a [..., s] mask is given."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - target_logit
    if mask is not None:
        maskf = mask.astype(jnp.float32)
        return jnp.sum(nll * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)
    return jnp.mean(nll)


def chunked_token_nll(x: jax.Array, head: jax.Array, targets: jax.Array,
                      mask: Optional[jax.Array], chunk: int) -> jax.Array:
    """Mean NLL without materializing the full [b, s, vocab] fp32 logits.

    Scans the sequence in `chunk`-sized pieces; each piece's lm-head matmul
    + softmax runs under jax.checkpoint, so the backward pass recomputes a
    [b, chunk, vocab] tile at a time instead of holding ~b*s*vocab*4 bytes
    of logits (2+ GiB at 8x2048x32k) resident. The lm-head recompute is
    ~2dV/token extra FLOPs — under 10% of the model forward — traded for
    the HBM working set, which is what lets bigger batches fit.
    """
    b, s, d = x.shape
    n_chunks = s // chunk
    assert n_chunks * chunk == s, (s, chunk)
    xs = x.reshape(b, n_chunks, chunk, d).swapaxes(0, 1)      # [nc, b, c, d]
    ts = targets.reshape(b, n_chunks, chunk).swapaxes(0, 1)   # [nc, b, c]
    maskf = (mask.astype(jnp.float32) if mask is not None
             else jnp.ones_like(targets, jnp.float32))
    ms = maskf.reshape(b, n_chunks, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_nll(x_c, t_c, m_c):
        logits = (x_c @ head).astype(jnp.float32)             # [b, c, V]
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return ((logz - tgt) * m_c).sum()

    def body(acc, xs_t):
        x_c, t_c, m_c = xs_t
        return acc + chunk_nll(x_c, t_c, m_c), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, ms))
    return total / jnp.maximum(maskf.sum(), 1.0)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            cfg: ModelConfig, mesh=None):
    """Next-token cross entropy.

    batch: either {"tokens": [b, s+1]} (shifted here) or pre-shifted
    {"inputs": [b, s], "targets": [b, s]} — the latter keeps s divisible by
    the sp axis for sequence parallelism. Optional {"loss_mask": [b, s]}.
    """
    inputs, targets, mask = split_batch(batch)
    if cfg.loss_chunk:
        if targets.shape[-1] % cfg.loss_chunk != 0:
            raise ValueError(
                f"loss_chunk={cfg.loss_chunk} must divide the target length "
                f"{targets.shape[-1]} (note {{'tokens'}} batches lose one "
                f"position to the shift)")
        x, moe_aux = forward_features_with_aux(params, inputs, cfg, mesh=mesh)
        loss = chunked_token_nll(x, lm_head_weights(params, cfg), targets,
                                 mask, cfg.loss_chunk)
    else:
        logits, moe_aux = forward_with_aux(params, inputs, cfg, mesh=mesh)
        loss = token_nll(logits, targets, mask)
    if cfg.n_experts > 0:
        loss = loss + cfg.moe_aux_weight * moe_aux
    return loss, {"loss": loss, "ntokens": targets.size, "moe_aux": moe_aux}
