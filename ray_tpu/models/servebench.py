"""SERVEBENCH: serving-engine performance artifact (decode fast lanes).

Measures what the continuous-batching engine actually delivers, separated
the way capacity planning needs it:

  * ``decode_tokens_per_s`` / per chip — steady-state fused-decode
    throughput with every slot busy (the flagship row; bounds rollout
    tokens/s for a serve+train fleet);
  * a slot sweep (1/4/8) — how throughput scales with continuous-batching
    occupancy;
  * bf16 vs w8a16 — the quantized engine on the SAME fast loop, with a
    logits-parity check so the quantized row is honest, and the measured
    weight-bytes ratio to validate (or retract) the "weight traffic
    halves" claim on this backend;
  * ``prefill_tokens_per_s`` — batched bucketed admission throughput,
    reported separately from decode (they bound different phases);
  * p50/p99 request latency under the storm harness's open-loop load
    generator driving a real Serve deployment of `LLMDeployment`.

Run:

    python -m ray_tpu.models.servebench                # quick profile
    python -m ray_tpu.models.servebench --json SERVEBENCH_r16.json \
        --baseline /tmp/servebench_baseline.json       # embed pre-change run

Artifact-regeneration policy: the committed SERVEBENCH_r{N}.json is a
full quick-profile run on the committing box; CI re-runs the same profile
and fails on missing rows, while `tests/test_envelope.py` pins machine-
calibrated floors on the decode/prefill rows (0.5x-slack discipline).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

DEFAULT_ARTIFACT = "SERVEBENCH_r16.json"

# Quick-profile model: small enough to compile/run on a 1-CPU CI box in
# seconds, big enough (GQA 8/4 heads, 4 layers) that the decode loop has
# the same shape as the flagship configs. dtype stays f32 on CPU — the
# "bf16" label tracks the flagship intent; the artifact records the real
# dtype of the run.
_QUICK = dict(vocab_size=2048, d_model=256, n_layers=4, n_heads=8,
              n_kv_heads=4, d_ff=1024, max_seq_len=512)
_QUICK_MAX_LEN = 512
_PROMPT = [1, 2, 3, 4, 5, 6, 7]


def _bench_model(quick: bool = True):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import ModelConfig, init_params

    if quick:
        cfg = ModelConfig(dtype=jnp.float32, remat="none", **_QUICK)
        max_len = _QUICK_MAX_LEN
    else:
        cfg = ModelConfig.b1()
        max_len = 2048
    params = init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg, max_len


def measure_decode(params, cfg, *, num_slots: int, max_len: int,
                   steps: int = 40, warm_steps: int = 10,
                   quantize_weights: bool = False) -> Dict[str, float]:
    """Steady-state decode throughput with every slot occupied. The warmup
    compiles the admission + decode kernels and the measured window stays
    inside one attention bucket, so the number is pure decode-loop speed
    (bucket recompiles are a once-per-depth cost, not a per-token one)."""
    from ray_tpu.models.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(params, cfg, num_slots=num_slots,
                                   max_len=max_len,
                                   quantize_weights=quantize_weights)
    for i in range(num_slots):
        eng.submit([t + i for t in _PROMPT], max_new_tokens=10 ** 6)
    for _ in range(warm_steps):
        eng.step()
    t0 = time.perf_counter()
    for _ in range(steps):
        eng.step()
    dt = time.perf_counter() - t0
    steps_per_s = steps / dt
    return {
        "num_slots": num_slots,
        "steps_per_s": round(steps_per_s, 2),
        "decode_tokens_per_s": round(steps_per_s * num_slots, 2),
        "ms_per_step": round(1e3 * dt / steps, 3),
    }


def measure_prefill(params, cfg, *, max_len: int, bucket: int = 64,
                    batch: int = 4, iters: int = 8) -> Dict[str, float]:
    """Batched bucketed admission throughput: one `prefill_slots` call per
    iteration over `batch` right-padded prompts of `bucket` tokens."""
    import jax.numpy as jnp

    from ray_tpu.models.serving import prefill_slots

    tokens = jnp.tile(jnp.arange(1, bucket + 1, dtype=jnp.int32)[None],
                      (batch, 1))
    true_len = jnp.full((batch,), bucket, jnp.int32)
    first, k, v = prefill_slots(params, tokens, true_len, cfg, max_len)
    np.asarray(first)  # compile + settle
    t0 = time.perf_counter()
    for _ in range(iters):
        first, k, v = prefill_slots(params, tokens, true_len, cfg, max_len)
    np.asarray(first)
    dt = time.perf_counter() - t0
    return {
        "batch": batch,
        "prompt_len": bucket,
        "prefill_tokens_per_s": round(iters * batch * bucket / dt, 1),
        "ms_per_call": round(1e3 * dt / iters, 3),
    }


def measure_quant_parity(params, cfg, *, max_len: int) -> Dict[str, Any]:
    """Honesty check for the w8a16 row: logits max-abs-diff (relative to
    the unquantized logit scale) on a probe prompt, plus the measured
    weight-bytes ratio (the "weight traffic halves" claim is about bytes
    read per decode step — on an HBM-bound TPU decode that ratio IS the
    speedup bound; on a compute-bound CPU it is not)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.inference import prefill
    from ray_tpu.models.serving import quantize_model_params

    qparams = quantize_model_params(params, cfg)

    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(tree))

    tokens = jnp.asarray([_PROMPT + [9, 22, 7]], jnp.int32)
    ref, _ = prefill(params, tokens, cfg, max_len)
    qlog, _ = prefill(qparams, tokens, cfg, max_len)
    ref = np.asarray(ref, np.float32)
    qlog = np.asarray(qlog, np.float32)
    rel = float(np.abs(ref - qlog).max() / (np.abs(ref).max() + 1e-6))
    return {
        "logits_max_abs_diff_rel": round(rel, 5),
        "logits_parity_ok": rel < 0.08,
        "weight_bytes_ratio": round(nbytes(qparams) / nbytes(params), 4),
    }


def measure_latency_under_load(params, cfg, *, max_len: int,
                               num_slots: int = 8, duration_s: float = 5.0,
                               rps: float = 6.0, max_new_tokens: int = 16,
                               request_timeout_s: float = 20.0
                               ) -> Dict[str, Any]:
    """p50/p99 request latency for a REAL Serve deployment of
    `LLMDeployment` (replica engine in driver mode via the
    `__serve_start__` hook) under the storm harness's open-loop load
    generator. Needs an initialized ray_tpu runtime."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models.serving import LLMDeployment
    from ray_tpu.serve.storm import LoadGenerator
    from ray_tpu.util.stats import percentile

    D = serve.deployment(name="servebench_llm", num_replicas=1,
                         max_concurrent_queries=num_slots)(
        LLMDeployment(params, cfg, num_slots=num_slots, max_len=max_len))
    handle = serve.run(D.bind(), name="servebench")
    try:
        # warm: compile prefill/admission/decode variants before the clock
        for wave in (num_slots, num_slots // 2 or 1, 2, 1):
            ray_tpu.get([handle.remote({"prompt": _PROMPT,
                                        "max_new_tokens": max_new_tokens})
                         for _ in range(wave)], timeout=120)
        gen = LoadGenerator(
            handle, rps=rps, request_timeout_s=request_timeout_s,
            payload_fn=lambda idx, i: {"prompt": _PROMPT,
                                       "max_new_tokens": max_new_tokens},
            threads=2)
        out = gen.run(duration_s)
        lat = sorted(out.latencies_ms)
        return {
            "offered_rps": rps,
            "duration_s": round(gen.elapsed_s, 2),
            "max_new_tokens": max_new_tokens,
            "submitted": out.submitted,
            "accepted": out.accepted,
            "shed": out.shed,
            "timeout": out.timeout,
            "errors": out.replica_death + out.other_error,
            "hung": out.hung,
            "p50_ms": round(percentile(lat, 0.50) or 0.0, 2),
            "p99_ms": round(percentile(lat, 0.99) or 0.0, 2),
        }
    finally:
        serve.delete("servebench_llm")


def run_servebench(quick: bool = True, *,
                   slot_sweep: Sequence[int] = (1, 4, 8),
                   with_latency: bool = True,
                   baseline: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    import jax

    params, cfg, max_len = _bench_model(quick)
    devices = jax.devices()
    n_chips = len(devices)

    sweep = [measure_decode(params, cfg, num_slots=s, max_len=max_len)
             for s in slot_sweep]
    flagship = sweep[-1]
    quant = measure_quant_parity(params, cfg, max_len=max_len)
    quant_decode = measure_decode(params, cfg, num_slots=slot_sweep[-1],
                                  max_len=max_len, quantize_weights=True)
    speed_ratio = (quant_decode["decode_tokens_per_s"]
                   / max(flagship["decode_tokens_per_s"], 1e-9))
    # The claim: int8 weights halve weight traffic, so HBM-bound decode
    # speeds up ~2x. Validated only where decode IS weight-traffic-bound;
    # a compute-bound backend (CPU) pays dequant FLOPs instead. Record the
    # verdict for THIS backend rather than asserting the TPU story.
    backend = jax.default_backend()
    quant_row = {
        **quant,
        "decode_tokens_per_s": quant_decode["decode_tokens_per_s"],
        "speedup_vs_unquantized": round(speed_ratio, 3),
        "weight_traffic_halves_claim": {
            "weight_bytes_ratio": quant["weight_bytes_ratio"],
            "bytes_claim_validated": quant["weight_bytes_ratio"] <= 0.55,
            "throughput_claim_validated_on_this_backend":
                speed_ratio >= 1.5,
            "backend": backend,
            "note": ("weight bytes shrink as claimed; the 2x decode "
                     "speedup only follows where decode is weight-"
                     "traffic-bound (TPU HBM), not on a compute-bound "
                     f"backend like {backend}" if speed_ratio < 1.5 else
                     "validated end to end on this backend"),
        },
    }
    prefill_row = measure_prefill(params, cfg, max_len=max_len)

    art: Dict[str, Any] = {
        "bench": "servebench",
        "round": 16,
        "profile": "quick" if quick else "full",
        "backend": backend,
        "n_chips": n_chips,
        "model": {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "n_kv_heads": cfg.n_kv_heads,
            "d_ff": cfg.d_ff, "vocab_size": cfg.vocab_size,
            "dtype": str(cfg.dtype.__name__
                         if hasattr(cfg.dtype, "__name__") else cfg.dtype),
            "max_len": max_len,
        },
        "decode": {
            "decode_tokens_per_s": flagship["decode_tokens_per_s"],
            "decode_tokens_per_s_per_chip": round(
                flagship["decode_tokens_per_s"] / n_chips, 2),
            "steps_per_s": flagship["steps_per_s"],
            "ms_per_step": flagship["ms_per_step"],
            "num_slots": flagship["num_slots"],
        },
        "slot_sweep": sweep,
        "w8a16": quant_row,
        "prefill": prefill_row,
    }
    if baseline is not None:
        art["baseline_pre_change"] = baseline
        base = baseline.get("slot_sweep", baseline)
        key = str(flagship["num_slots"])
        base_row = base.get(key) if isinstance(base, dict) else None
        if base_row and base_row.get("decode_tokens_per_s"):
            art["decode"]["speedup_vs_baseline"] = round(
                flagship["decode_tokens_per_s"]
                / base_row["decode_tokens_per_s"], 2)
    if with_latency:
        import ray_tpu

        owns_runtime = not ray_tpu.is_initialized()
        if owns_runtime:
            ray_tpu.init(num_cpus=8, resources={"TPU": 8})
        try:
            art["latency_under_load"] = measure_latency_under_load(
                params, cfg, max_len=max_len)
        finally:
            if owns_runtime:
                try:
                    from ray_tpu import serve

                    serve.shutdown()
                finally:
                    ray_tpu.shutdown()
    return art


REQUIRED_ROWS = ("decode", "slot_sweep", "w8a16", "prefill",
                 "latency_under_load")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", default=DEFAULT_ARTIFACT,
                    help=f"artifact path (default {DEFAULT_ARTIFACT}; "
                         f"'' to skip writing)")
    ap.add_argument("--full", action="store_true",
                    help="flagship-config profile (TPU-sized; default is "
                         "the quick CI profile)")
    ap.add_argument("--no-latency", action="store_true",
                    help="skip the serve-deployment latency rows (no "
                         "runtime spin-up)")
    ap.add_argument("--baseline", default=None,
                    help="JSON file with pre-change decode numbers to "
                         "embed as baseline_pre_change")
    args = ap.parse_args(argv)

    baseline = None
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)
    art = run_servebench(quick=not args.full,
                         with_latency=not args.no_latency,
                         baseline=baseline)

    dec = art["decode"]
    print(f"servebench [{art['profile']}] backend={art['backend']} "
          f"chips={art['n_chips']}")
    print(f"  decode: {dec['decode_tokens_per_s']} tok/s "
          f"({dec['decode_tokens_per_s_per_chip']} tok/s/chip, "
          f"{dec['ms_per_step']} ms/step @ {dec['num_slots']} slots"
          + (f", {dec['speedup_vs_baseline']}x vs pre-change baseline"
             if "speedup_vs_baseline" in dec else "") + ")")
    print("  slots  steps/s  tok/s")
    for row in art["slot_sweep"]:
        print(f"  {row['num_slots']:>5}  {row['steps_per_s']:>7} "
              f"{row['decode_tokens_per_s']:>6}")
    q = art["w8a16"]
    print(f"  w8a16: {q['decode_tokens_per_s']} tok/s "
          f"({q['speedup_vs_unquantized']}x vs unquantized), "
          f"weight bytes {q['weight_bytes_ratio']}x, "
          f"logits rel err {q['logits_max_abs_diff_rel']}")
    print(f"  prefill: {art['prefill']['prefill_tokens_per_s']} tok/s "
          f"(batch {art['prefill']['batch']} x "
          f"{art['prefill']['prompt_len']} tokens)")
    if "latency_under_load" in art:
        lat = art["latency_under_load"]
        print(f"  latency under load: p50 {lat['p50_ms']}ms "
              f"p99 {lat['p99_ms']}ms ({lat['accepted']}/{lat['submitted']} "
              f"accepted @ {lat['offered_rps']} rps, hung={lat['hung']})")

    missing = [r for r in REQUIRED_ROWS
               if r not in art and not (r == "latency_under_load"
                                        and args.no_latency)]
    if missing:
        print(f"SERVEBENCH FAILED: missing rows {missing}")
        return 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(art, f, indent=2, sort_keys=True)
        print(f"  artifact: {args.json}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
