"""HuggingFace <-> ray_tpu weight conversion for the Llama model family.

The reference has no model zoo (it wraps torch models; SURVEY §2.4), but its
Train/RLlib users bring HF checkpoints — this module gives those users the
same on-ramp: `load_hf_llama()` maps a `transformers` LlamaForCausalLM
(object, state dict, or local checkpoint path) onto the layer-stacked
`ray_tpu.models.transformer` pytree.

Conventions line up exactly: HF Llama uses half-split ("rotate_half") RoPE
with inv_freq = theta^(-2i/d), the same scheme as `ops/layers.apply_rotary`
— so projections map with plain transposes, no head permutation. HF linear
weights are stored [out, in] and applied as x @ W.T; ours are stored
[in, out] and applied as x @ W, hence every projection transposes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ray_tpu.models.transformer import ModelConfig


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> ModelConfig:
    """ModelConfig from a transformers LlamaConfig(-compatible) object."""
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        dtype=dtype,
    )


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, dtype=np.float32)


def params_from_hf_state_dict(state: Dict[str, Any], cfg: ModelConfig
                              ) -> Dict[str, Any]:
    """Map an HF LlamaForCausalLM state dict to the transformer pytree.

    Accepts either `model.`-prefixed keys (full LlamaForCausalLM) or bare
    ones (LlamaModel). Layer leaves are stacked on a leading L axis to
    match `init_params` / the lax.scan forward.
    """
    pre = "model." if any(k.startswith("model.") for k in state) else ""

    def get(key: str) -> np.ndarray:
        return _to_np(state[key])

    def stacked(fmt: str, transpose: bool) -> jnp.ndarray:
        mats = [get(fmt.format(i=i)) for i in range(cfg.n_layers)]
        arr = np.stack([m.T if transpose else m for m in mats])
        return jnp.asarray(arr).astype(cfg.dtype)

    layers = {
        "attn_norm": stacked(pre + "layers.{i}.input_layernorm.weight", False),
        "wq": stacked(pre + "layers.{i}.self_attn.q_proj.weight", True),
        "wk": stacked(pre + "layers.{i}.self_attn.k_proj.weight", True),
        "wv": stacked(pre + "layers.{i}.self_attn.v_proj.weight", True),
        "wo": stacked(pre + "layers.{i}.self_attn.o_proj.weight", True),
        "mlp_norm": stacked(pre + "layers.{i}.post_attention_layernorm.weight",
                            False),
        "w_gate": stacked(pre + "layers.{i}.mlp.gate_proj.weight", True),
        "w_up": stacked(pre + "layers.{i}.mlp.up_proj.weight", True),
        "w_down": stacked(pre + "layers.{i}.mlp.down_proj.weight", True),
    }
    params: Dict[str, Any] = {
        "embed": jnp.asarray(get(pre + "embed_tokens.weight")).astype(cfg.dtype),
        "final_norm": jnp.asarray(get(pre + "norm.weight")).astype(cfg.dtype),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        head = state.get("lm_head.weight")
        if head is None:
            raise ValueError(
                "state dict has no lm_head.weight but cfg.tie_embeddings is "
                "False — pass a full LlamaForCausalLM state dict, or set "
                "tie_embeddings=True if the checkpoint ties the output head "
                "to the embeddings")
        params["lm_head"] = jnp.asarray(_to_np(head).T).astype(cfg.dtype)
    return params


def load_hf_llama(model_or_path: Any, dtype: Any = jnp.bfloat16
                  ) -> Tuple[Dict[str, Any], ModelConfig]:
    """(params, cfg) from an HF model object or local checkpoint path."""
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(model_or_path)
    else:
        model = model_or_path
    cfg = config_from_hf(model.config, dtype=dtype)
    params = params_from_hf_state_dict(model.state_dict(), cfg)
    return params, cfg


def state_dict_from_params(params: Dict[str, Any], cfg: ModelConfig
                           ) -> Dict[str, np.ndarray]:
    """Inverse mapping, for exporting trained weights back to HF tooling."""
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["embed"], dtype=np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T
    names = {
        "attn_norm": ("input_layernorm.weight", False),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    for ours, (theirs, transpose) in names.items():
        stack = np.asarray(params["layers"][ours], np.float32)
        for i in range(cfg.n_layers):
            m = stack[i]
            out[f"model.layers.{i}.{theirs}"] = m.T if transpose else m
    return out
