"""Continuous-batching LLM engine: slot-based KV cache, join/leave per step.

The serving-side scheduler the reference lacks natively (it serves torch
models behind Serve replicas): requests occupy fixed cache *slots* so the
decode step is one compiled function over static shapes — sequences join
(prefill writes their KV rows into a free slot) and retire (EOS/length)
between steps without recompiling, the continuous-batching idea of Orca /
vLLM re-built TPU-first (static shapes for XLA, per-row positions instead
of dynamic batch).

Engine = pure-JAX step functions + a host-side slot manager. Serve wires it
through `LLMDeployment` (serve replicas each host an engine; Serve's p2c
router spreads requests across replicas).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.inference import _masked_attention, _mlp, _project_qkv
from ray_tpu.models.transformer import (ModelConfig, _deq_tree,
                                        _embed_lookup, lm_head_weights)
from ray_tpu.ops.layers import rms_norm, rotary_embedding


_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_model_params(params: Dict, cfg: ModelConfig) -> Dict:
    """w8a16 load-time quantization (the serving-engine consumer of
    `ops.pallas.quant.quantize_int8`): every projection matrix, the
    embedding table, and the lm head become `{"int8", "scale"}` leaves with
    per-row absmax scales — ~2x less weight HBM and 2x less weight traffic
    per decode step (decode is HBM-bound). Norm vectors stay in bf16: they
    are 0.01% of the bytes and norm math is fp32 anyway. The model's
    forward paths dequantize on read inside the layer scan."""
    from ray_tpu.ops.pallas.quant import quantize_int8

    def q(w):
        values, scales = quantize_int8(w)
        return {"int8": values, "scale": scales}

    out = dict(params)
    out["layers"] = {
        k: (q(v) if k in _QUANT_LEAVES else v)
        for k, v in params["layers"].items()
    }
    out["embed"] = q(params["embed"])
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"])
    return out


@functools.partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill_kv(params: Dict, tokens: jax.Array, true_len: jax.Array,
               cfg: ModelConfig, max_len: int):
    """Prompt pass for ONE right-padded request [1, s_bucket]: returns
    (logits at true_len-1 [vocab], k [L, kvh, max_len, hd], v likewise).

    Prompts are padded to bucket lengths before this call so XLA compiles
    once per bucket, not once per prompt length; the causal mask makes
    positions < true_len independent of the padding."""
    from ray_tpu.models.inference import prefill

    logits, cache = prefill(params, tokens, cfg, max_len,
                            logits_index=true_len[None] - 1)
    return logits[0], cache["k"][:, 0], cache["v"][:, 0]


def _bucket_len(n: int, max_len: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return min(b, max_len - 1)


@functools.partial(jax.jit, static_argnames=("cfg",))
def decode_slots(params: Dict, k_all: jax.Array, v_all: jax.Array,
                 lengths: jax.Array, tokens: jax.Array, cfg: ModelConfig):
    """One decode step over all slots with per-slot positions.

    k_all/v_all: [L, B, kvh, max_len, hd]; lengths [B] (current position per
    slot); tokens [B] (last sampled token per slot). Returns (logits [B, V],
    new k_all, new v_all). Inactive slots compute garbage harmlessly.
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    max_len = k_all.shape[-2]
    cos, sin = rotary_embedding(lengths[:, None], hd, cfg.rope_theta)  # [B,1,hd/2]
    x = _embed_lookup(params["embed"], tokens[:, None], cfg.dtype)  # [B,1,d]
    mask = jnp.arange(max_len)[None, None, :] <= lengths[:, None, None]  # [B,1,L]

    def write_row(cache, new, pos):
        # cache [kvh, max_len, hd] <- new [kvh, 1, hd] at position pos
        return jax.lax.dynamic_update_slice(cache, new, (0, pos, 0))

    def attend_mask(q, kc, vc, m):
        # per-row mask variant of _masked_attention: m [1, max_len]
        return _masked_attention(q[None], kc[None], vc[None], m)[0]

    def body(x, inputs):
        lp, k_cache, v_cache = inputs  # caches [B, kvh, max_len, hd]
        lp = _deq_tree(lp, cfg.dtype)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, h, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        k_cache = jax.vmap(write_row)(k_cache, k.astype(cfg.dtype), lengths)
        v_cache = jax.vmap(write_row)(v_cache, v.astype(cfg.dtype), lengths)
        attn = jax.vmap(attend_mask)(q, k_cache, v_cache, mask)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, 1, cfg.n_heads * hd)
        x = x + (attn @ lp["wo"]).astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, lp, h2).astype(x.dtype)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(body, x, (params["layers"], k_all, v_all))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ lm_head_weights(params, cfg)).astype(jnp.float32)
    return logits, k_new, v_new


@dataclasses.dataclass
class _Request:
    request_id: int
    prompt: List[int]
    max_new_tokens: int
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    done: bool = False


class ContinuousBatchingEngine:
    """Host-side slot manager over the jitted prefill/decode kernels."""

    def __init__(self, params: Dict, cfg: ModelConfig, *, num_slots: int = 4,
                 max_len: int = 512, eos_token: Optional[int] = None,
                 quantize_weights: bool = False):
        if quantize_weights:
            params = quantize_model_params(params, cfg)
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token = eos_token
        L, kvh, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        self.k = jnp.zeros((L, num_slots, kvh, max_len, hd), cfg.dtype)
        self.v = jnp.zeros((L, num_slots, kvh, max_len, hd), cfg.dtype)
        self.lengths = jnp.zeros((num_slots,), jnp.int32)
        self.tokens = jnp.zeros((num_slots,), jnp.int32)
        self._free = list(range(num_slots))
        self._active: Dict[int, _Request] = {}   # slot -> request
        self._waiting: List[_Request] = []
        self._finished: Dict[int, _Request] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- requests
    def submit(self, prompt: List[int], *, max_new_tokens: int = 32) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) >= self.max_len - 1:
            raise ValueError(
                f"prompt length {len(prompt)} must be < max_len-1 = "
                f"{self.max_len - 1}")
        with self._lock:
            req = _Request(self._next_id, list(prompt), max_new_tokens)
            self._next_id += 1
            self._waiting.append(req)
            return req.request_id

    def _admit(self) -> None:
        while self._waiting and self._free:
            req = self._waiting.pop(0)
            slot = self._free.pop()
            req.slot = slot
            n = len(req.prompt)
            padded = req.prompt + [0] * (_bucket_len(n, self.max_len) - n)
            logits, k_rows, v_rows = prefill_kv(
                self.params, jnp.asarray([padded], jnp.int32),
                jnp.asarray(n, jnp.int32), self.cfg, self.max_len)
            first = int(jnp.argmax(logits))
            req.generated.append(first)
            self.k = self.k.at[:, slot].set(k_rows)
            self.v = self.v.at[:, slot].set(v_rows)
            self.lengths = self.lengths.at[slot].set(len(req.prompt))
            self.tokens = self.tokens.at[slot].set(first)
            self._active[slot] = req
            self._maybe_finish(req)

    def _maybe_finish(self, req: _Request) -> None:
        hit_eos = self.eos_token is not None and req.generated and \
            req.generated[-1] == self.eos_token
        out_of_room = len(req.prompt) + len(req.generated) >= self.max_len - 1
        if len(req.generated) >= req.max_new_tokens or hit_eos or out_of_room:
            req.done = True
            if req.slot >= 0:
                self._active.pop(req.slot, None)
                self._free.append(req.slot)
                req.slot = -1
            self._finished[req.request_id] = req

    # ----------------------------------------------------------------- step
    def step(self) -> int:
        """Admit waiting requests, run one decode step; returns number of
        sequences still active."""
        with self._lock:
            self._admit()
            if not self._active:
                return 0
            logits, self.k, self.v = decode_slots(
                self.params, self.k, self.v, self.lengths, self.tokens,
                self.cfg)
            nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
            self.lengths = self.lengths + 1  # every slot advanced (inactive: junk)
            new_tokens = np.array(self.tokens)  # writable copy
            for slot, req in list(self._active.items()):
                tok = int(nxt[slot])
                req.generated.append(tok)
                new_tokens[slot] = tok
                self._maybe_finish(req)
            self.tokens = jnp.asarray(new_tokens)
            return len(self._active) + len(self._waiting)

    def run_until_done(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if self.step() == 0 and not self._waiting:
                return

    # -------------------------------------------------------------- results
    def result(self, request_id: int) -> Optional[List[int]]:
        with self._lock:
            req = self._finished.get(request_id)
            if req is None:
                return None
            toks = req.prompt + req.generated
            if self.eos_token is not None and toks and toks[-1] == self.eos_token:
                toks = toks[:-1]
            return toks

    def progress(self, request_id: int):
        """(tokens generated so far, done) — readable while decoding, for
        token streaming. Mirrors result(): a trailing EOS is stripped, so
        streamed output always equals the non-streamed suffix."""
        with self._lock:
            req = self._finished.get(request_id)
            if req is not None:
                toks = list(req.generated)
                if (self.eos_token is not None and toks
                        and toks[-1] == self.eos_token):
                    toks.pop()
                return toks, True
            for req in list(self._active.values()) + self._waiting:
                if req.request_id == request_id:
                    return list(req.generated), req.done
        return [], True  # unknown id

    def generate(self, prompt: List[int], *, max_new_tokens: int = 32
                 ) -> List[int]:
        rid = self.submit(prompt, max_new_tokens=max_new_tokens)
        while self.result(rid) is None:
            if self.step() == 0 and self.result(rid) is None and \
                    not self._waiting:
                break
        return self.result(rid) or []

    def generate_stream(self, prompt: List[int], *,
                        max_new_tokens: int = 32):
        """Generator yielding tokens AS DECODED (continuous batching keeps
        serving other slots between yields) — the engine half of
        Serve token streaming (reference vLLM-style streaming generate)."""
        rid = self.submit(prompt, max_new_tokens=max_new_tokens)
        emitted = 0
        while True:
            active = self.step()
            toks, done = self.progress(rid)
            while emitted < len(toks):
                yield int(toks[emitted])
                emitted += 1
            if done:
                return
            if active == 0:
                return  # nothing left anywhere; request never finished


def LLMDeployment(params, cfg: ModelConfig, *, num_slots: int = 4,
                  max_len: int = 512, eos_token: Optional[int] = None,
                  quantize_weights: bool = False):
    """A serve-ready callable class hosting one engine per replica.

    Usage:
        from ray_tpu import serve
        D = serve.deployment(LLMDeployment(params, cfg))
        handle = serve.run(D.bind())
        handle.remote({"prompt": [1, 2, 3], "max_new_tokens": 8})
    """

    class _LLM:
        def __init__(self):
            self.engine = ContinuousBatchingEngine(
                params, cfg, num_slots=num_slots, max_len=max_len,
                eos_token=eos_token, quantize_weights=quantize_weights)

        def __call__(self, payload):
            prompt = list(payload["prompt"])
            n = int(payload.get("max_new_tokens", 32))
            return self.engine.generate(prompt, max_new_tokens=n)

        def stream(self, payload):
            """Streaming entry: call through a stream handle
            (`handle.options(method_name='stream', stream=True)`) or HTTP
            `POST /<name>/stream?stream=1` — tokens arrive as generated."""
            prompt = list(payload["prompt"])
            n = int(payload.get("max_new_tokens", 32))
            yield from self.engine.generate_stream(prompt, max_new_tokens=n)

    _LLM.__name__ = "LLMDeployment"
    return _LLM
