"""Continuous-batching LLM engine: slot-based KV cache, join/leave per step.

The serving-side scheduler the reference lacks natively (it serves torch
models behind Serve replicas): requests occupy fixed cache *slots* so the
decode step is one compiled function over static shapes — sequences join
(prefill writes their KV rows into a free slot) and retire (EOS/length)
between steps without recompiling, the continuous-batching idea of Orca /
vLLM re-built TPU-first (static shapes for XLA, per-row positions instead
of dynamic batch).

Engine = pure-JAX step functions + a host-side slot manager. The decode
loop is built to run at device speed:

  * `decode_step_fused` donates the K/V/length buffers (the cache update
    is in-place — no per-step reallocation of [L, slots, kvh, max_len, hd])
    and fuses greedy sampling on-device, so only a [slots] int32 token
    array ever crosses to the host;
  * attention reads a power-of-2 *bucket* of the cache (compiled once per
    bucket) instead of all max_len rows, so short sequences pay for the
    cache they use;
  * `step()` runs one step of *lookahead*: it dispatches step N+1 before
    syncing step N's tokens, so host bookkeeping (EOS/finish/admit, slot
    accounting) overlaps device compute — at the cost of one junk slot-step
    per retiring request (its slot computes garbage once before the host
    notices the EOS);
  * admission is batched: all same-bucket waiting requests prefill in ONE
    `prefill_slots` call and their prefix KV is scattered straight into the
    donated slot cache (`_write_slots`), first tokens sampled on device.

Device waits happen OUTSIDE the bookkeeping lock: `submit()`, `progress()`
and `result()` stay responsive while a step is in flight (`_step_lock`
serializes steppers; `_lock` only guards host-side state).

Serve wires it through `LLMDeployment` (serve replicas each host an
engine; the replica lifecycle hooks `__serve_start__`/`__serve_stop__`
start and stop a background driver thread so the engine steps itself and
callers just wait on their request).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.inference import (_gqa_decode_attention, _masked_attention,
                                      _mlp, _project_qkv)
from ray_tpu.models.transformer import (ModelConfig, _deq_tree,
                                        _embed_lookup, lm_head_weights)
from ray_tpu.ops.layers import rms_norm, rotary_embedding

logger = logging.getLogger(__name__)

_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

# attention never reads fewer cache rows than this — keeps the number of
# compiled bucket variants small (64, 128, 256, ... max_len)
_ATTN_BUCKET_MIN = 64


def quantize_model_params(params: Dict, cfg: ModelConfig) -> Dict:
    """w8a16 load-time quantization (the serving-engine consumer of
    `ops.pallas.quant.quantize_int8`): every projection matrix, the
    embedding table, and the lm head become `{"int8", "scale"}` leaves with
    per-row absmax scales — ~2x less weight HBM and 2x less weight traffic
    per decode step (decode is HBM-bound). Norm vectors stay in bf16: they
    are 0.01% of the bytes and norm math is fp32 anyway. The model's
    forward paths dequantize on read inside the layer scan."""
    from ray_tpu.ops.pallas.quant import quantize_int8

    def q(w):
        values, scales = quantize_int8(w)
        return {"int8": values, "scale": scales}

    out = dict(params)
    out["layers"] = {
        k: (q(v) if k in _QUANT_LEAVES else v)
        for k, v in params["layers"].items()
    }
    out["embed"] = q(params["embed"])
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"])
    return out


@functools.partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill_kv(params: Dict, tokens: jax.Array, true_len: jax.Array,
               cfg: ModelConfig, max_len: int):
    """Prompt pass for ONE right-padded request [1, s_bucket]: returns
    (logits at true_len-1 [vocab], k [L, kvh, max_len, hd], v likewise).

    Prompts are padded to bucket lengths before this call so XLA compiles
    once per bucket, not once per prompt length; the causal mask makes
    positions < true_len independent of the padding. The engine's admission
    path uses the batched `prefill_slots` instead; this stays as the
    single-request entry point."""
    from ray_tpu.models.inference import prefill

    logits, cache = prefill(params, tokens, cfg, max_len,
                            logits_index=true_len[None] - 1)
    return logits[0], cache["k"][:, 0], cache["v"][:, 0]


@functools.partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill_slots(params: Dict, tokens: jax.Array, true_len: jax.Array,
                  cfg: ModelConfig, max_len: int):
    """Batched prompt pass over one admission bucket [nb, s_bucket]: every
    same-bucket waiting request prefills in a single compiled call. Returns
    (first greedy tokens [nb] — sampled ON DEVICE, no logits cross to the
    host — and the prefix caches k/v [L, nb, kvh, max_len, hd])."""
    from ray_tpu.models.inference import prefill

    logits, cache = prefill(params, tokens, cfg, max_len,
                            logits_index=true_len - 1)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return first, cache["k"], cache["v"]


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _write_slots(k_all: jax.Array, v_all: jax.Array, lengths: jax.Array,
                 tokens: jax.Array, slots: jax.Array, k_rows: jax.Array,
                 v_rows: jax.Array, true_len: jax.Array, first: jax.Array):
    """Admission scatter: write a prefill bucket's KV rows straight into
    the DONATED slot cache (in-place update — the cache is never cloned to
    admit). `slots` entries equal to num_slots are batch padding and are
    dropped by the out-of-bounds scatter mode. `tokens` is deliberately NOT
    donated: the in-flight decode step still reads the previous buffer."""
    k_all = k_all.at[:, slots].set(k_rows, mode="drop")
    v_all = v_all.at[:, slots].set(v_rows, mode="drop")
    lengths = lengths.at[slots].set(true_len, mode="drop")
    tokens = tokens.at[slots].set(first, mode="drop")
    return k_all, v_all, lengths, tokens


def _bucket_len(n: int, max_len: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return min(b, max_len - 1)


def _attn_bucket(pos: int, max_len: int) -> int:
    """Power-of-2 attention window >= the deepest active position (strict
    mask: position pos attends cache rows [0, pos))."""
    b = min(_ATTN_BUCKET_MIN, max_len)
    while b < pos:
        b *= 2
    return min(b, max_len)


@functools.partial(jax.jit, static_argnames=("cfg",))
def decode_slots(params: Dict, k_all: jax.Array, v_all: jax.Array,
                 lengths: jax.Array, tokens: jax.Array, cfg: ModelConfig):
    """One decode step over all slots with per-slot positions (legacy
    entry: returns host-visible logits and NON-donated caches — the engine
    uses `decode_step_fused`; this stays for callers that need logits).

    k_all/v_all: [L, B, kvh, max_len, hd]; lengths [B] (current position per
    slot); tokens [B] (last sampled token per slot). Returns (logits [B, V],
    new k_all, new v_all). Inactive slots compute garbage harmlessly.
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    max_len = k_all.shape[-2]
    cos, sin = rotary_embedding(lengths[:, None], hd, cfg.rope_theta)  # [B,1,hd/2]
    x = _embed_lookup(params["embed"], tokens[:, None], cfg.dtype)  # [B,1,d]
    mask = jnp.arange(max_len)[None, None, :] <= lengths[:, None, None]  # [B,1,L]

    def write_row(cache, new, pos):
        # cache [kvh, max_len, hd] <- new [kvh, 1, hd] at position pos
        return jax.lax.dynamic_update_slice(cache, new, (0, pos, 0))

    def attend_mask(q, kc, vc, m):
        # per-row mask variant of _masked_attention: m [1, max_len]
        return _masked_attention(q[None], kc[None], vc[None], m)[0]

    def body(x, inputs):
        lp, k_cache, v_cache = inputs  # caches [B, kvh, max_len, hd]
        lp = _deq_tree(lp, cfg.dtype)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, h, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        k_cache = jax.vmap(write_row)(k_cache, k.astype(cfg.dtype), lengths)
        v_cache = jax.vmap(write_row)(v_cache, v.astype(cfg.dtype), lengths)
        attn = jax.vmap(attend_mask)(q, k_cache, v_cache, mask)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, 1, cfg.n_heads * hd)
        x = x + (attn @ lp["wo"]).astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, lp, h2).astype(x.dtype)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(body, x, (params["layers"], k_all, v_all))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ lm_head_weights(params, cfg)).astype(jnp.float32)
    return logits, k_new, v_new


@functools.partial(jax.jit, static_argnames=("cfg", "attn_len"),
                   donate_argnums=(1, 2, 3))
def decode_step_fused(params: Dict, k_all: jax.Array, v_all: jax.Array,
                      lengths: jax.Array, tokens: jax.Array,
                      cfg: ModelConfig, attn_len: int):
    """The hot decode step: one token for every slot, greedy sampling fused
    on device, K/V/length buffers DONATED so the cache row-write is a true
    in-place scatter (no [L, B, kvh, max_len, hd] reallocation per step).

    Structure matters for the donation to be real: the caches enter the
    layer scan as READ-ONLY xs — a scan that carries the cache through its
    ys gets double-buffered by XLA even when the final output aliases the
    input. Attention therefore splits into (cache window) + (current
    token's own K/V, which is not written yet — STRICT mask `< lengths`),
    and the per-layer K/V rows are written afterwards in one donated
    scatter outside the scan.

    `attn_len` is the static attention window (a power-of-2 bucket >= every
    active position): XLA compiles one executable per bucket and short
    sequences stop paying O(max_len) attention.

    Returns (k_all, v_all, lengths+1, next_tokens [B] int32) — the caller
    keeps everything on device; only `next_tokens` is ever synced, one
    step late. `tokens` is NOT donated (the lookahead pipeline reads step
    N's token buffer after step N+1 is dispatched).
    """
    B = tokens.shape[0]
    hd = cfg.head_dim
    rep = cfg.n_heads // cfg.n_kv_heads
    cos, sin = rotary_embedding(lengths[:, None], hd, cfg.rope_theta)
    x = _embed_lookup(params["embed"], tokens[:, None], cfg.dtype)  # [B,1,d]
    mask = jnp.arange(attn_len)[None, :] < lengths[:, None]  # [B, attn_len]

    def body(x, inputs):
        lp, k_cache, v_cache = inputs  # read-only [B, kvh, max_len, hd]
        lp = _deq_tree(lp, cfg.dtype)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, h, cos, sin)
        q = q.transpose(0, 2, 1, 3)  # [B, h, 1, hd]
        k_cur = k.transpose(0, 2, 1, 3)[:, :, 0].astype(cfg.dtype)  # [B,kvh,hd]
        v_cur = v.transpose(0, 2, 1, 3)[:, :, 0].astype(cfg.dtype)
        attn = _gqa_decode_attention(
            q, k_cache[:, :, :attn_len], v_cache[:, :, :attn_len],
            k_cur, v_cur, mask)
        attn = attn.reshape(B, 1, cfg.n_heads * hd)
        x = x + (attn @ lp["wo"]).astype(x.dtype)
        h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, lp, h2).astype(x.dtype)
        return x, (k_cur, v_cur)

    x, (k_cur, v_cur) = jax.lax.scan(body, x, (params["layers"], k_all, v_all))
    # k_cur/v_cur [L, B, kvh, hd] -> one donated row-scatter per cache
    def write_row(cache, new, pos):
        # cache [max_len, hd] <- new [1, hd] at row pos
        return jax.lax.dynamic_update_slice(cache, new, (pos, 0))

    wr = jax.vmap(jax.vmap(jax.vmap(write_row, in_axes=(0, 0, None)),  # kvh
                           in_axes=(0, 0, 0)),                         # B
                  in_axes=(0, 0, None))                                # L
    k_all = wr(k_all, k_cur[:, :, :, None], lengths)
    v_all = wr(v_all, v_cur[:, :, :, None], lengths)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ lm_head_weights(params, cfg)).astype(jnp.float32)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return k_all, v_all, lengths + 1, nxt


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class _Request:
    request_id: int
    prompt: List[int]
    max_new_tokens: int
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    done: bool = False


class ContinuousBatchingEngine:
    """Host-side slot manager over the jitted prefill/decode kernels.

    Locking: `_step_lock` serializes steppers (at most one step pipeline in
    flight); `_lock` guards only host bookkeeping and is NEVER held across
    a device wait — streaming `progress()` reads and `submit()` complete
    while a step is blocked on the device. `_cv` (on `_lock`) wakes waiters
    when tokens land and wakes the driver thread when work arrives.
    """

    def __init__(self, params: Dict, cfg: ModelConfig, *, num_slots: int = 4,
                 max_len: int = 512, eos_token: Optional[int] = None,
                 quantize_weights: bool = False):
        if quantize_weights:
            params = quantize_model_params(params, cfg)
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token = eos_token
        L, kvh, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        self.k = jnp.zeros((L, num_slots, kvh, max_len, hd), cfg.dtype)
        self.v = jnp.zeros((L, num_slots, kvh, max_len, hd), cfg.dtype)
        self.lengths = jnp.zeros((num_slots,), jnp.int32)
        self.tokens = jnp.zeros((num_slots,), jnp.int32)
        self._free = list(range(num_slots))
        self._active: Dict[int, _Request] = {}   # slot -> request
        self._waiting: List[_Request] = []
        self._finished: Dict[int, _Request] = {}
        self._next_id = 0
        # host shadow of each slot's position: lets the dispatcher pick the
        # attention bucket without ever syncing `lengths` off the device
        self._slot_pos = [0] * num_slots
        # in-flight decode: (device tokens [B], {slot: request} captured at
        # dispatch time — attribution survives the slot being freed/reused)
        self._pending: Optional[Tuple[jax.Array, Dict[int, _Request]]] = None
        # admissions whose on-device first token hasn't been synced yet:
        # [(device first-tokens [nb_pad], [(row, request), ...])]
        self._pending_first: List[Tuple[jax.Array, List[Tuple[int, _Request]]]] = []
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._step_lock = threading.Lock()
        self._driver: Optional[threading.Thread] = None
        self._driver_stop = False
        self._driver_error: Optional[BaseException] = None

    # ------------------------------------------------------------- requests
    def submit(self, prompt: List[int], *, max_new_tokens: int = 32) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) >= self.max_len - 1:
            raise ValueError(
                f"prompt length {len(prompt)} must be < max_len-1 = "
                f"{self.max_len - 1}")
        with self._lock:
            req = _Request(self._next_id, list(prompt), max_new_tokens)
            self._next_id += 1
            self._waiting.append(req)
            self._cv.notify_all()
            return req.request_id

    def _maybe_finish(self, req: _Request) -> None:
        hit_eos = self.eos_token is not None and req.generated and \
            req.generated[-1] == self.eos_token
        out_of_room = len(req.prompt) + len(req.generated) >= self.max_len - 1
        if len(req.generated) >= req.max_new_tokens or hit_eos or out_of_room:
            req.done = True
            if req.slot >= 0:
                self._active.pop(req.slot, None)
                self._free.append(req.slot)
                self._slot_pos[req.slot] = 0
                req.slot = -1
            self._finished[req.request_id] = req

    # ----------------------------------------------------------------- step
    @staticmethod
    def _to_host(arr: jax.Array) -> np.ndarray:
        """THE host sync point (device wait). Routed through one method so
        tests can instrument it; always called WITHOUT `_lock` held."""
        return np.asarray(arr)

    def step(self) -> int:
        """Admit waiting requests (batched, bucketed), dispatch the next
        decode step, then sync + bookkeep the PREVIOUS step's tokens while
        the new one runs on device. Returns sequences still active."""
        with self._step_lock:
            return self._step_inner()

    def _step_inner(self) -> int:
        with self._lock:
            admissions = self._collect_admissions()
        for bucket, reqs in admissions:
            self._dispatch_prefill(bucket, reqs)      # device enqueue only
        with self._lock:
            prev = self._pending
            self._pending = self._dispatch_decode()   # device enqueue only
        self._drain_pending_first()                   # device wait, no _lock
        self._reap(prev)                              # device wait, no _lock
        with self._lock:
            return len(self._active) + len(self._waiting)

    def _collect_admissions(self):
        """Pop waiting requests into free slots, grouped by prompt bucket
        (one batched prefill per bucket). Caller holds `_lock`."""
        by_bucket: Dict[int, List[_Request]] = {}
        while self._waiting and self._free:
            req = self._waiting.pop(0)
            slot = self._free.pop()
            req.slot = slot
            self._active[slot] = req
            self._slot_pos[slot] = len(req.prompt)
            bucket = _bucket_len(len(req.prompt), self.max_len)
            by_bucket.setdefault(bucket, []).append(req)
        return sorted(by_bucket.items())

    def _dispatch_prefill(self, bucket: int, reqs: List[_Request]) -> None:
        """ONE `prefill_slots` call for every same-bucket admission; the
        prefix KV goes straight into the donated slot cache. The batch is
        padded to a power of 2 (padding rows scatter to an out-of-range
        slot and are dropped) so XLA compiles per (nb, bucket), not per
        admission count. First tokens stay on device until bookkeeping."""
        rows = [r.prompt + [0] * (bucket - len(r.prompt)) for r in reqs]
        lens = [len(r.prompt) for r in reqs]
        slots = [r.slot for r in reqs]
        for _ in range(_pow2(len(reqs)) - len(reqs)):
            rows.append([0] * bucket)
            lens.append(1)
            slots.append(self.num_slots)  # out of range -> dropped
        first, k_rows, v_rows = prefill_slots(
            self.params, jnp.asarray(rows, jnp.int32),
            jnp.asarray(lens, jnp.int32), self.cfg, self.max_len)
        self.k, self.v, self.lengths, self.tokens = _write_slots(
            self.k, self.v, self.lengths, self.tokens,
            jnp.asarray(slots, jnp.int32), k_rows, v_rows,
            jnp.asarray(lens, jnp.int32), first)
        self._pending_first.append(
            (first, [(i, r) for i, r in enumerate(reqs)]))

    def _dispatch_decode(self):
        """Dispatch one fused decode step (no device wait). Captures the
        dispatch-time active set so tokens are attributed correctly even if
        a slot retires and is re-admitted before the sync. Caller holds
        `_lock`."""
        if not self._active:
            return None
        attn_len = _attn_bucket(
            max(self._slot_pos[s] for s in self._active), self.max_len)
        slot_map = dict(self._active)
        self.k, self.v, self.lengths, tokens_out = decode_step_fused(
            self.params, self.k, self.v, self.lengths, self.tokens,
            self.cfg, attn_len)
        self.tokens = tokens_out
        for s in slot_map:
            self._slot_pos[s] += 1
        return tokens_out, slot_map

    def _drain_pending_first(self) -> None:
        """Sync admissions' on-device first tokens (deferred from dispatch
        so prefill overlaps the decode step queued behind it)."""
        if not self._pending_first:
            return
        batches, self._pending_first = self._pending_first, []
        for first_dev, entries in batches:
            first = self._to_host(first_dev)  # device wait — no _lock held
            with self._lock:
                for row, req in entries:
                    req.generated.append(int(first[row]))
                    self._maybe_finish(req)
                self._cv.notify_all()

    def _reap(self, prev) -> None:
        """Sync + bookkeep a previously dispatched step's tokens. Runs
        while the NEXT step computes on device (one-step lookahead)."""
        if prev is None:
            return
        tokens_dev, slot_map = prev
        nxt = self._to_host(tokens_dev)  # device wait — no _lock held
        with self._lock:
            for slot, req in slot_map.items():
                if req.done:
                    continue  # finished at dispatch+1; this token is junk
                req.generated.append(int(nxt[slot]))
                self._maybe_finish(req)
            self._cv.notify_all()

    def run_until_done(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if self.step() == 0 and not self._waiting:
                return

    # ------------------------------------------------------- driver thread
    def start_driver(self) -> None:
        """Background thread that steps the engine whenever there is work:
        callers then just `submit()` and `wait()`/stream. Used by serve
        replicas via the `__serve_start__` lifecycle hook."""
        with self._lock:
            if self._driver is not None:
                return
            self._driver_stop = False
            self._driver_error = None
            self._driver = threading.Thread(
                target=self._drive, name="engine-driver", daemon=True)
            self._driver.start()

    def stop_driver(self, timeout: float = 5.0) -> None:
        with self._lock:
            t = self._driver
            if t is None:
                return
            self._driver_stop = True
            self._cv.notify_all()
        t.join(timeout)
        with self._lock:
            self._driver = None

    def _has_work(self) -> bool:
        return bool(self._waiting or self._active or self._pending
                    or self._pending_first)

    def _drive(self) -> None:
        while True:
            with self._lock:
                while not self._driver_stop and not self._has_work():
                    self._cv.wait(0.1)
                if self._driver_stop:
                    return
            try:
                self.step()
            except Exception as e:  # surface to waiters instead of hanging
                logger.exception("engine driver thread died")
                with self._lock:
                    self._driver_error = e
                    self._driver = None
                    self._cv.notify_all()
                return

    # -------------------------------------------------------------- results
    def _result_locked(self, req: _Request) -> List[int]:
        toks = req.prompt + req.generated
        if self.eos_token is not None and toks and toks[-1] == self.eos_token:
            toks = toks[:-1]
        return toks

    def result(self, request_id: int) -> Optional[List[int]]:
        with self._lock:
            req = self._finished.get(request_id)
            if req is None:
                return None
            return self._result_locked(req)

    def wait(self, request_id: int,
             timeout: Optional[float] = None) -> List[int]:
        """Block until `request_id` finishes (driver mode). Raises if the
        driver died or the timeout expires."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while request_id not in self._finished:
                if self._driver_error is not None:
                    raise self._driver_error
                if self._driver is None and not self._has_work():
                    raise RuntimeError(
                        "engine has no driver and no work in flight; "
                        "call step() or start_driver()")
                remaining = 0.1 if deadline is None else \
                    min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError(
                        f"request {request_id} not done within {timeout}s")
                self._cv.wait(remaining)
            return self._result_locked(self._finished[request_id])

    def _progress_locked(self, request_id: int):
        req = self._finished.get(request_id)
        if req is not None:
            toks = list(req.generated)
            if (self.eos_token is not None and toks
                    and toks[-1] == self.eos_token):
                toks.pop()
            return toks, True
        for req in list(self._active.values()) + self._waiting:
            if req.request_id == request_id:
                return list(req.generated), req.done
        return [], True  # unknown id

    def progress(self, request_id: int):
        """(tokens generated so far, done) — readable while decoding, for
        token streaming. Mirrors result(): a trailing EOS is stripped, so
        streamed output always equals the non-streamed suffix. Takes only
        the bookkeeping lock: never blocks behind a device wait."""
        with self._lock:
            return self._progress_locked(request_id)

    def generate(self, prompt: List[int], *, max_new_tokens: int = 32,
                 timeout: Optional[float] = None) -> List[int]:
        rid = self.submit(prompt, max_new_tokens=max_new_tokens)
        if self._driver is not None:
            return self.wait(rid, timeout=timeout)
        while self.result(rid) is None:
            if self.step() == 0 and self.result(rid) is None and \
                    not self._waiting:
                break
        return self.result(rid) or []

    def generate_stream(self, prompt: List[int], *,
                        max_new_tokens: int = 32):
        """Generator yielding tokens AS DECODED (continuous batching keeps
        serving other slots between yields) — the engine half of
        Serve token streaming (reference vLLM-style streaming generate)."""
        rid = self.submit(prompt, max_new_tokens=max_new_tokens)
        emitted = 0
        if self._driver is not None:
            while True:
                with self._lock:
                    while True:
                        toks, done = self._progress_locked(rid)
                        if len(toks) > emitted or done:
                            break
                        if self._driver_error is not None:
                            raise self._driver_error
                        self._cv.wait(0.2)
                while emitted < len(toks):  # yield OUTSIDE the lock
                    yield int(toks[emitted])
                    emitted += 1
                if done:
                    return
        while True:
            active = self.step()
            toks, done = self.progress(rid)
            while emitted < len(toks):
                yield int(toks[emitted])
                emitted += 1
            if done:
                return
            if active == 0:
                return  # nothing left anywhere; request never finished


def LLMDeployment(params, cfg: ModelConfig, *, num_slots: int = 4,
                  max_len: int = 512, eos_token: Optional[int] = None,
                  quantize_weights: bool = False):
    """A serve-ready callable class hosting one engine per replica.

    Usage:
        from ray_tpu import serve
        D = serve.deployment(LLMDeployment(params, cfg))
        handle = serve.run(D.bind())
        handle.remote({"prompt": [1, 2, 3], "max_new_tokens": 8})

    Inside a replica the `__serve_start__` lifecycle hook starts the
    engine's background driver thread, so concurrent requests all ride one
    continuously-batched decode loop (each caller blocks only on its own
    request); standalone (no hook) the engine self-steps in the caller.
    """

    class _LLM:
        def __init__(self):
            self.engine = ContinuousBatchingEngine(
                params, cfg, num_slots=num_slots, max_len=max_len,
                eos_token=eos_token, quantize_weights=quantize_weights)

        def __serve_start__(self):
            self.engine.start_driver()

        def __serve_stop__(self):
            self.engine.stop_driver()

        def __call__(self, payload):
            prompt = list(payload["prompt"])
            n = int(payload.get("max_new_tokens", 32))
            return self.engine.generate(prompt, max_new_tokens=n)

        def stream(self, payload):
            """Streaming entry: call through a stream handle
            (`handle.options(method_name='stream', stream=True)`) or HTTP
            `POST /<name>/stream?stream=1` — tokens arrive as generated."""
            prompt = list(payload["prompt"])
            n = int(payload.get("max_new_tokens", 32))
            yield from self.engine.generate_stream(prompt, max_new_tokens=n)

    _LLM.__name__ = "LLMDeployment"
    return _LLM
