"""State observability API: list cluster entities.

Mirrors the reference's state API surface
(`python/ray/experimental/state/api.py:115` — `ray list actors/tasks/
nodes/...` and `ray summary`), backed by the GCS tables and the task-event
buffer instead of a separate aggregator service.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.core.api import _global_worker


def list_nodes() -> List[Dict[str, Any]]:
    w = _global_worker()
    out = []
    for n in w.gcs.call("get_all_nodes"):
        out.append({
            "node_id": n["node_id"].hex(),
            "address": n["address"],
            "alive": n["alive"],
            "resources_total": n["resources_total"],
            "resources_available": n["resources_available"],
            "labels": n.get("labels", {}),
        })
    return out


def list_actors() -> List[Dict[str, Any]]:
    w = _global_worker()
    out = []
    for a in w.gcs.call("list_actors"):
        out.append({
            "actor_id": a["actor_id"].hex(),
            "class_name": a.get("class_name", ""),
            "name": a.get("name"),
            "state": a["state"],
            "address": a.get("address", ""),
            "num_restarts": a.get("num_restarts", 0),
        })
    return out


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    w = _global_worker()
    out = []
    for t in w.gcs.call("list_task_events", {"limit": limit}):
        if "__truncated__" in t:
            # history window overflowed: surface it instead of presenting a
            # silently-complete-looking listing (weak spot flagged in review)
            out.append({"task_id": "", "name": "(truncated)",
                        "type": "META", "state":
                        f"+{t['__truncated__']} older tasks evicted",
                        "node_id": ""})
            continue
        out.append({
            "task_id": t["task_id"].hex(),
            "name": t.get("name", ""),
            "type": t.get("type", ""),
            "state": t.get("state", ""),
            "node_id": t.get("node_id", b"").hex() if t.get("node_id") else "",
        })
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    w = _global_worker()
    out = []
    for p in w.gcs.call("list_placement_groups"):
        out.append({
            "placement_group_id": p["pg_id"].hex(),
            "state": p["state"],
            "strategy": p["strategy"],
            "bundles": p["bundles"],
            "name": p.get("name"),
        })
    return out


def list_jobs() -> List[Dict[str, Any]]:
    w = _global_worker()
    out = []
    for j in w.gcs.call("get_jobs"):
        out.append({
            "job_id": j["job_id"].hex(),
            "status": j.get("status"),
            "start_time": j.get("start_time"),
        })
    return out


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for t in list_tasks():
        key = f"{t['name']}:{t['state']}"
        counts[key] = counts.get(key, 0) + 1
    return counts
