"""CLI: `python -m ray_tpu <command>`.

Command surface mirrors the reference CLI (SURVEY appendix A): start,
status, list (actors/nodes/tasks/pgs/jobs), summary, timeline, job submit.
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "start":
        # argparse REMAINDER can't forward leading options; dispatch directly
        from ray_tpu.core.node_main import main as node_main

        node_main(argv[1:])
        return 0

    if argv and argv[0] == "client-server":
        from ray_tpu.client.server import main as client_server_main

        return client_server_main(argv[1:])

    if argv and argv[0] in ("up", "down", "exec", "submit", "attach"):
        # cluster launcher (reference `ray up/down/exec/attach/submit`,
        # scripts.py:1223): dispatched directly — exec/submit forward
        # arbitrary trailing commands argparse REMAINDER would mangle
        from ray_tpu.autoscaler import launcher as _launcher

        cmd, rest = argv[0], argv[1:]
        if not rest or rest[0] in ("-h", "--help"):
            print(f"usage: ray_tpu {cmd} cluster.yaml ...", file=sys.stderr)
            return 0 if rest else 2
        yaml_path = rest[0]
        try:
            if cmd == "up":
                return _launcher.cli_up(yaml_path,
                                        block="--block" in rest[1:])
            if cmd == "down":
                return _launcher.cli_down(yaml_path)
            if cmd == "exec":
                if len(rest) < 2:
                    print("usage: ray_tpu exec cluster.yaml -- cmd ...",
                          file=sys.stderr)
                    return 2
                cmd_args = rest[1:]
                if cmd_args and cmd_args[0] == "--":
                    cmd_args = cmd_args[1:]
                return _launcher.cli_exec(yaml_path, cmd_args)
            if cmd == "submit":
                if len(rest) < 2:
                    print("usage: ray_tpu submit cluster.yaml script.py ...",
                          file=sys.stderr)
                    return 2
                return _launcher.cli_submit(yaml_path, rest[1], rest[2:])
            return _launcher.cli_attach(yaml_path)
        except (FileNotFoundError, ValueError) as e:
            # bad yaml path / malformed config: one-line error, not a trace
            print(f"ray_tpu {cmd}: {e}", file=sys.stderr)
            return 2

    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("start", help="start a node daemon (head or worker)")

    p_status = sub.add_parser("status", help="cluster resource summary")
    p_status.add_argument("--address", required=True)

    p_list = sub.add_parser("list", help="list cluster entities")
    p_list.add_argument("what", choices=["actors", "nodes", "tasks",
                                         "placement-groups", "jobs"])
    p_list.add_argument("--address", required=True)

    p_sum = sub.add_parser("summary", help="task state summary")
    p_sum.add_argument("--address", required=True)

    p_tl = sub.add_parser("timeline", help="dump chrome trace json")
    p_tl.add_argument("--output", default="timeline.json")
    p_tl.add_argument("--address", default=None,
                      help="GCS address: include cluster-wide worker spans")
    p_tl.add_argument("--trace", default=None, metavar="TRACE_ID",
                      help="one causal tree only (requires --address); "
                           "'list' prints recent trace ids instead")

    p_tr = sub.add_parser(
        "trace", help="critical-path breakdown of one task "
        "(submit -> lease -> dispatch -> run -> result-deliver)")
    p_tr.add_argument("task_id", help="task id hex (ray_tpu list tasks)")
    p_tr.add_argument("--address", required=True)

    p_mem = sub.add_parser("memory", help="object store usage per node")
    p_mem.add_argument("--address", required=True)

    p_jobs = sub.add_parser(
        "jobs", help="per-job state: status, live/detached actors, "
        "pending tasks, owned bytes, fate-sharing reap counters")
    p_jobs.add_argument("--address", required=True)

    p_logs = sub.add_parser("logs", help="recent worker stdout/stderr")
    p_logs.add_argument("--address", required=True)
    p_logs.add_argument("--lines", type=int, default=200)

    p_stack = sub.add_parser("stack", help="dump local worker stack traces")
    p_stack.add_argument("--address", required=True)

    p_prof = sub.add_parser(
        "profile",
        help="on-demand cpu/memory profile of live workers (py-spy role)")
    p_prof.add_argument("--address", required=True)
    p_prof.add_argument("--pid", type=int, default=None,
                        help="one worker pid (default: every worker)")
    p_prof.add_argument("--kind", choices=("cpu", "memory"), default="cpu")
    p_prof.add_argument("--duration", type=float, default=5.0)
    p_prof.add_argument("--output", default=None,
                        help="write full JSON here (default: print summary)")

    p_health = sub.add_parser("healthcheck", help="exit 0 if GCS responds")
    p_health.add_argument("--address", required=True)

    p_gc = sub.add_parser("global-gc", help="gc.collect() in every worker")
    p_gc.add_argument("--address", required=True)

    p_chaos = sub.add_parser("kill-random-node",
                             help="chaos: hard-kill a random non-head node")
    p_chaos.add_argument("--address", required=True)

    sub.add_parser("microbenchmark", help="core-primitive ops/s suite")

    p_env = sub.add_parser(
        "envelope", help="scalability-envelope suite (tasks/actors/PGs/"
        "broadcast + microbenchmark), writes a JSON artifact")
    p_env.add_argument("--out", default=None)
    p_env.add_argument("--scale", type=float, default=1.0)
    p_env.add_argument("--elastic", action="store_true",
                       help="also run the burst-elasticity chaos scenario")

    p_serve = sub.add_parser("serve", help="model serving")
    serve_sub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    p_sv_deploy = serve_sub.add_parser("deploy")
    p_sv_deploy.add_argument("config_file")
    p_sv_deploy.add_argument("--address", required=True)
    p_sv_status = serve_sub.add_parser("status")
    p_sv_status.add_argument("--address", required=True)
    p_sv_down = serve_sub.add_parser("shutdown")
    p_sv_down.add_argument("--address", required=True)
    p_sv_run = serve_sub.add_parser(
        "run", help="import module:deployment and serve.run it")
    p_sv_run.add_argument("import_path", help="module.sub:attr")
    p_sv_run.add_argument("--address", required=True)
    p_sv_run.add_argument("--port", type=int, default=8000)
    p_sv_run.add_argument("--blocking", action="store_true")

    p_job = sub.add_parser("job", help="job submission")
    job_sub = p_job.add_subparsers(dest="job_cmd", required=True)
    p_job_submit = job_sub.add_parser("submit")
    p_job_submit.add_argument("--address", required=True)
    p_job_submit.add_argument("--working-dir", default=None)
    p_job_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p_job_list = job_sub.add_parser("list")
    p_job_list.add_argument("--address", required=True)
    for cmdname in ("status", "logs", "stop"):
        p = job_sub.add_parser(cmdname)
        p.add_argument("job_id")
        p.add_argument("--address", required=True)

    p_rllib = sub.add_parser("rllib", help="RL training (reference rllib CLI)")
    rllib_sub = p_rllib.add_subparsers(dest="rllib_cmd", required=True)
    p_rl_train = rllib_sub.add_parser("train")
    p_rl_train.add_argument("--algo", required=True,
                            help="registered algorithm, e.g. ppo/dqn/impala")
    p_rl_train.add_argument("--stop-iters", type=int, default=10)
    p_rl_train.add_argument("--stop-reward", type=float, default=None)
    p_rl_train.add_argument("--num-workers", type=int, default=2)
    p_rl_train.add_argument("--checkpoint-path", default=None)
    p_rl_eval = rllib_sub.add_parser("evaluate")
    p_rl_eval.add_argument("--algo", required=True)
    p_rl_eval.add_argument("--checkpoint-path", required=True)
    p_rl_eval.add_argument("--episodes", type=int, default=5)

    p_debug = sub.add_parser("debug",
                             help="attach to a remote rpdb breakpoint")
    p_debug.add_argument("--address", required=True)
    p_debug.add_argument("--index", type=int, default=0,
                         help="which breakpoint (from the listed order)")

    p_metrics = sub.add_parser("metrics", help="observability tooling")
    metrics_sub = p_metrics.add_subparsers(dest="metrics_cmd", required=True)
    p_mx = metrics_sub.add_parser(
        "export-dashboards",
        help="write Grafana dashboard JSON for provisioning")
    p_mx.add_argument("--out-dir", default="./grafana_dashboards")
    p_mx.add_argument("--which", nargs="*", default=None,
                      choices=["core", "train", "serve"])

    args = parser.parse_args(argv)

    if args.cmd == "debug":
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.util import rpdb

        gcs = _rpc.connect_with_retry(args.address, timeout=5)
        try:
            bps = rpdb.list_breakpoints(gcs)
        finally:
            gcs.close()
        if not bps:
            print("no active breakpoints")
            return 1
        for i, bp in enumerate(bps):
            print(f"[{i}] pid={bp.get('pid')} {bp['host']}:{bp['port']} "
                  f"task={bp.get('task_id')} actor={bp.get('actor_id')}")
        bp = bps[min(args.index, len(bps) - 1)]
        print(f"attaching to {bp['host']}:{bp['port']} "
              f"(Ctrl-D to detach)...")
        rpdb.attach(bp["host"], bp["port"])
        return 0

    if args.cmd == "metrics":
        from ray_tpu.grafana import export_dashboards

        for path in export_dashboards(args.out_dir, args.which):
            print(f"wrote {path}")
        return 0

    if args.cmd == "status":
        rt = _connect(args.address)
        print(json.dumps({
            "total": rt.cluster_resources(),
            "available": rt.available_resources(),
            "nodes": len(rt.nodes()),
        }, indent=2))
        return 0

    if args.cmd == "list":
        _connect(args.address)
        from ray_tpu import state

        fn = {
            "actors": state.list_actors,
            "nodes": state.list_nodes,
            "tasks": state.list_tasks,
            "placement-groups": state.list_placement_groups,
            "jobs": state.list_jobs,
        }[args.what]
        print(json.dumps(fn(), indent=2, default=str))
        return 0

    if args.cmd == "summary":
        _connect(args.address)
        from ray_tpu import state

        print(json.dumps(state.summarize_tasks(), indent=2))
        return 0

    if args.cmd == "microbenchmark":
        from ray_tpu.microbenchmark import main as micro_main

        return micro_main([])

    if args.cmd == "envelope":
        from ray_tpu.envelope import main as env_main

        argv = []
        if args.out:
            argv += ["--out", args.out]
        argv += ["--scale", str(args.scale)]
        if args.elastic:
            argv += ["--elastic"]
        return env_main(argv)

    if args.cmd == "timeline":
        from ray_tpu.util import timeline as _timeline
        from ray_tpu.util import tracing

        if args.trace and not args.address:
            print("--trace requires --address", file=sys.stderr)
            return 2
        extra = []
        offsets = {}
        if args.address:
            from ray_tpu.core import rpc as _rpc

            gcs = _rpc.connect_with_retry(args.address, timeout=5)
            try:
                if args.trace == "list":
                    for t in gcs.call("list_traces", {"limit": 50},
                                      timeout=10):
                        print(f"{t['trace_id']}  spans={t['spans']:<6d} "
                              f"last_ts_us={t['last_ts_us']:.0f}")
                    return 0
                if args.trace:
                    reply = gcs.call("get_trace",
                                     {"trace_id": args.trace}, timeout=10)
                    doc = _timeline.merge_chrome(reply["spans"],
                                                 reply.get("offsets"))
                    with open(args.output, "w") as f:
                        json.dump(doc, f)
                    print(f"wrote {args.output} "
                          f"({len(doc['traceEvents'])} spans of trace "
                          f"{args.trace})")
                    return 0
                extra = gcs.call("get_profile_events", timeout=10)
                offsets = gcs.call("get_span_offsets", timeout=10)
            finally:
                gcs.close()
        # fleet-merged dump: local ring + every span the GCS holds, clock-
        # aligned per source and time-sorted into one chrome document
        doc = _timeline.merge_chrome(
            tracing.get_events() + list(extra or []), offsets)
        with open(args.output, "w") as f:
            json.dump(doc, f)
        print(f"wrote {args.output}")
        return 0

    if args.cmd == "trace":
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.util import timeline as _timeline

        gcs = _rpc.connect_with_retry(args.address, timeout=5)
        try:
            reply = gcs.call("get_trace", {"task_id": args.task_id},
                             timeout=10)
            stats = gcs.call("gcs_stats", timeout=10)
        finally:
            gcs.close()
        spans = _timeline.apply_offsets(reply.get("spans") or [],
                                        reply.get("offsets") or {})
        segs = _timeline.stage_segments(spans, args.task_id)
        if not segs:
            print(f"no trace recorded for task {args.task_id} "
                  f"(is tracing on? RAY_TPU_TRACING_ENABLED=1)",
                  file=sys.stderr)
            return 1
        t0 = min(s[1] for s in segs)
        t_end = max(s[1] + s[2] for s in segs)
        print(f"task {args.task_id} (trace {reply.get('trace_id')}): "
              f"{(t_end - t0) / 1e3:.2f} ms submit -> result-deliver")
        prev_end = None
        for stage, start, dur in segs:
            gap = ""
            if prev_end is not None and start - prev_end > 50:
                gap = f"  (+{(start - prev_end) / 1e3:.2f} ms between)"
            print(f"  {stage:<15s} +{(start - t0) / 1e3:9.2f} ms  "
                  f"{dur / 1e3:8.2f} ms{gap}")
            prev_end = start + dur
        stage_lat = ((stats.get("tracing") or {})
                     .get("stage_latency_us") or {})
        if stage_lat:
            print("fleet stage latency, p50/p99 ms:")
            for stage in _timeline.STAGE_ORDER:
                s = stage_lat.get(stage)
                if s:
                    print(f"  {stage:<15s} "
                          f"{s['p50_us'] / 1e3:8.2f} / "
                          f"{s['p99_us'] / 1e3:8.2f}  (n={s['count']})")
        return 0

    if args.cmd in ("memory", "stack", "healthcheck", "global-gc",
                    "kill-random-node", "logs", "profile", "jobs"):
        # raw GCS/raylet RPC — no driver registration needed
        from ray_tpu.core import rpc as _rpc

        try:
            gcs = _rpc.connect_with_retry(args.address, timeout=5)
        except ConnectionError as e:
            if args.cmd == "healthcheck":
                print(f"unhealthy: {e}")
                return 1
            raise
        try:
            try:
                nodes = gcs.call("get_all_nodes", timeout=10)
            except Exception as e:
                if args.cmd == "healthcheck":
                    print(f"unhealthy: {e}")
                    return 1
                raise
            alive = [n for n in nodes if n["alive"]]
            if args.cmd == "healthcheck":
                print(json.dumps({"healthy": True, "alive_nodes": len(alive)}))
                return 0
            if args.cmd == "global-gc":
                gcs.call("global_gc")
                print("global gc triggered")
                return 0
            if args.cmd == "kill-random-node":
                import random

                victims = alive[1:] or alive  # prefer non-head
                if not victims:
                    print("no alive nodes to kill")
                    return 1
                v = random.choice(victims)
                c = _rpc.connect_with_retry(v["address"], timeout=5)
                try:
                    accepted = c.call("die", timeout=5)
                except (_rpc.RpcDisconnected, TimeoutError):
                    accepted = True  # died before replying — success
                finally:
                    c.close()
                if not accepted:
                    print(f"node {v['node_id'].hex()[:8]} refused "
                          f"(driver-embedded raylet)")
                    return 1
                print(f"killed node {v['node_id'].hex()[:8]}")
                return 0
            if args.cmd == "logs":
                for entry in gcs.call("get_recent_logs",
                                      {"lines": args.lines}):
                    for line in entry.get("lines", []):
                        print(f"(pid={entry.get('pid')}, "
                              f"{entry.get('stream')}) {line}")
                return 0
            if args.cmd == "jobs":
                st = gcs.call("gcs_stats", timeout=10)
                jobs_blk = st.get("jobs", [])
                # live per-driver numbers (pending tasks, owned bytes)
                # come from each RUNNING driver's own owner_stats RPC —
                # ownership lives in the driver, not the GCS
                for j in jobs_blk:
                    if j.get("status") != "RUNNING" \
                            or not j.get("driver_address"):
                        continue
                    try:
                        c = _rpc.connect_with_retry(j["driver_address"],
                                                    timeout=3)
                        try:
                            own = c.call("owner_stats", timeout=5)
                        finally:
                            c.close()
                        j["pending_tasks"] = own.get("pending_tasks")
                        j["owned_objects"] = own.get("owned_objects")
                        j["owned_bytes"] = own.get("owned_bytes")
                    except Exception as e:
                        j["owner_stats_error"] = str(e)
                print(json.dumps(
                    {"jobs": jobs_blk,
                     "job_failure": st.get("job_failure", {})},
                    indent=2, default=str))
                return 0
            if args.cmd == "memory":
                out = []
                for n in alive:
                    c = _rpc.connect_with_retry(n["address"], timeout=5)
                    st = c.call("object_store_stats")
                    st["node_id"] = st["node_id"].hex()
                    out.append(st)
                    c.close()
                # cluster storage roll-up (same block gcs_stats aggregates
                # from heartbeats, but computed live from the nodes here)
                storage = {
                    "used_bytes": sum(s.get("used_bytes", 0) for s in out),
                    "capacity_bytes": sum(s.get("capacity_bytes", 0)
                                          for s in out),
                    "pinned_bytes": sum(s.get("pinned_bytes", 0)
                                        for s in out),
                    "spilled_bytes": sum(s.get("spilled_bytes", 0)
                                         for s in out),
                    "nodes_spill_degraded": [
                        s["node_id"] for s in out if s.get("spill_degraded")],
                }
                print(json.dumps({"storage": storage, "nodes": out},
                                 indent=2))
                return 0
            if args.cmd == "profile":
                import time as _time

                from ray_tpu.util.profiler import (poll_profile_results,
                                                   trigger_profile)

                pending = trigger_profile(gcs, args.pid, args.kind,
                                          args.duration)
                if not pending:
                    print("no matching workers")
                    return 1
                reports, pending = poll_profile_results(
                    pending, _time.monotonic() + args.duration + 30,
                    poll_interval_s=min(args.duration / 2 + 0.2, 2.0))
                if args.output:
                    with open(args.output, "w") as fh:
                        json.dump(reports, fh, indent=2)
                    print(f"wrote {len(reports)} profiles to {args.output}")
                else:
                    for rep in reports:
                        print(f"==== pid {rep.get('pid')} "
                              f"({rep.get('kind')}) ====")
                        if rep.get("error"):
                            print(f"  error: {rep['error']}")
                        elif rep.get("kind") == "memory":
                            print(f"  rss {rep.get('rss_before')} -> "
                                  f"{rep.get('rss_after')}")
                            for site in rep.get("sites", [])[:10]:
                                print(f"  {site['size_bytes']:>12,}B "
                                      f"x{site['count']:<6} "
                                      f"{site['traceback'][-1].strip()}")
                        else:
                            total = sum(s["count"]
                                        for s in rep.get("stacks", []))
                            for s in rep.get("stacks", [])[:10]:
                                leaf = s["stack"].rsplit(";", 1)[-1]
                                pct = 100 * s["count"] / max(total, 1)
                                print(f"  {pct:5.1f}% {leaf}")
                if pending:
                    print(f"({len(pending)} workers did not report in time)")
                return 0
            if args.cmd == "stack":
                import os as _os
                import signal as _signal
                import time as _time

                stack_dir = "/tmp/ray_tpu/stacks"
                signaled = {}  # pid -> file offset before this dump
                for n in alive:
                    c = _rpc.connect_with_retry(n["address"], timeout=5)
                    for w in c.call("list_workers"):
                        path = _os.path.join(stack_dir, f"{w['pid']}.txt")
                        try:
                            offset = _os.path.getsize(path)
                        except OSError:
                            offset = 0
                        try:
                            _os.kill(w["pid"], _signal.SIGUSR1)
                            signaled[w["pid"]] = offset
                        except (ProcessLookupError, PermissionError):
                            continue
                    c.close()
                _time.sleep(0.5)
                # print only live workers' dumps, and only this invocation's
                # (faulthandler appends; earlier dumps are before offset)
                for pid, offset in sorted(signaled.items()):
                    path = _os.path.join(stack_dir, f"{pid}.txt")
                    try:
                        with open(path) as fh:
                            fh.seek(offset)
                            content = fh.read().strip()
                    except OSError:
                        continue
                    if content:
                        print(f"==== worker pid {pid} ====")
                        print(content)
                return 0
        finally:
            gcs.close()

    if args.cmd == "serve":
        _connect(args.address)
        from ray_tpu import serve

        if args.serve_cmd == "run":
            import importlib

            mod_name, _, attr = args.import_path.partition(":")
            if not attr:
                print("import_path must be module:attribute", file=sys.stderr)
                return 2
            sys.path.insert(0, "")
            target = getattr(importlib.import_module(mod_name), attr)
            serve.run(target.bind() if hasattr(target, "bind") else target)
            _, port = serve.start_http_proxy(port=args.port)
            print(f"serving on http://127.0.0.1:{port}")
            if args.blocking:
                import time as _time

                while True:
                    _time.sleep(3600)
            return 0
        if args.serve_cmd == "deploy":
            print(json.dumps(serve.deploy_config_file(args.config_file)))
        elif args.serve_cmd == "status":
            print(json.dumps(serve.status(), indent=2))
        else:
            serve.shutdown()
            print("serve shut down")
        return 0

    if args.cmd == "job":
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(args.address)
        if args.job_cmd == "submit":
            entry = args.entrypoint
            if entry and entry[0] == "--":
                entry = entry[1:]
            job_id = client.submit_job(
                entrypoint=" ".join(entry), working_dir=args.working_dir)
            print(job_id)
        elif args.job_cmd == "status":
            print(client.get_job_status(args.job_id))
        elif args.job_cmd == "logs":
            print(client.get_job_logs(args.job_id))
        elif args.job_cmd == "stop":
            ok = client.stop_job(args.job_id)
            print("stopped" if ok else "not running")
            return 0 if ok else 1
        else:
            print(json.dumps(client.list_jobs(), indent=2, default=str))
        return 0

    if args.cmd == "rllib":
        _connect(args.address) if hasattr(args, "address") else None
        import ray_tpu as _rt

        if not _rt.is_initialized():
            _rt.init(num_cpus=4)
        from ray_tpu import rllib as _rllib

        by_name = {n[:-6].lower(): getattr(_rllib, n) for n in dir(_rllib)
                   if n.endswith("Config")}
        cfg_cls = by_name.get(args.algo.lower())
        if cfg_cls is None:
            print(f"unknown algorithm {args.algo!r}; "
                  f"available: {' '.join(sorted(by_name))}")
            return 1
        cfg = cfg_cls()
        if hasattr(cfg, "rollouts") and args.rllib_cmd == "train":
            try:
                cfg.rollouts(num_rollout_workers=args.num_workers)
            except TypeError:
                pass
        algo = cfg.build()
        try:
            if args.rllib_cmd == "train":
                last = {}
                for i in range(args.stop_iters):
                    last = algo.train()
                    reward = last.get("episode_reward_mean")
                    print(f"iter {i + 1}: reward={reward}")
                    if (args.stop_reward is not None and reward is not None
                            and reward >= args.stop_reward):
                        break
                if args.checkpoint_path:
                    ckpt = algo.save()
                    ckpt.to_directory(args.checkpoint_path)
                    print(f"checkpoint: {args.checkpoint_path}")
            else:  # evaluate
                from ray_tpu.air.checkpoint import Checkpoint

                algo.restore(Checkpoint.from_directory(args.checkpoint_path))
                ev = algo.evaluate(num_episodes=args.episodes)
                print(json.dumps(ev, indent=2))
        finally:
            algo.stop()
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
