"""Grafana dashboard factory: default cluster + user-metric dashboards.

Reference parity: dashboard/modules/metrics/grafana_dashboard_factory.py —
emits Grafana dashboard JSON whose panels query the Prometheus metrics the
framework exports (`ray_tpu/dashboard.py` `/metrics`). `ray_tpu metrics
export-dashboards` (CLI) writes the JSON files a Grafana provisioning dir
can point at.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def _panel(panel_id: int, title: str, exprs: List[str], *,
           unit: str = "short", x: int = 0, y: int = 0,
           w: int = 12, h: int = 8) -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [
            {"expr": expr, "refId": chr(ord("A") + i), "legendFormat": ""}
            for i, expr in enumerate(exprs)
        ],
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
    }


def _dashboard(uid: str, title: str,
               panels: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "uid": uid,
        "title": title,
        "tags": ["ray_tpu"],
        "timezone": "browser",
        "refresh": "10s",
        "schemaVersion": 38,
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus", "label": "Data source",
        }]},
        "panels": panels,
        "time": {"from": "now-30m", "to": "now"},
    }


def generate_default_dashboard() -> Dict[str, Any]:
    """Core-runtime dashboard: tasks, actors, objects, nodes, scheduler."""
    rows = [
        ("Tasks finished", ["rate(ray_tpu_tasks_finished_total[1m])"],
         "ops"),
        ("Tasks pending", ["ray_tpu_tasks_pending"], "short"),
        ("Actors alive", ["ray_tpu_actors_alive"], "short"),
        ("Nodes alive", ["ray_tpu_nodes_alive"], "short"),
        ("Object store used bytes", ["ray_tpu_object_store_used_bytes",
                                     "ray_tpu_object_store_capacity_bytes"],
         "bytes"),
        ("Objects spilled to disk", ["ray_tpu_object_store_spilled_objects"],
         "short"),
    ]
    panels = []
    for i, (title, exprs, unit) in enumerate(rows):
        panels.append(_panel(i + 1, title, exprs, unit=unit,
                             x=(i % 2) * 12, y=(i // 2) * 8))
    return _dashboard("ray-tpu-core", "ray_tpu core", panels)


def generate_train_dashboard() -> Dict[str, Any]:
    """Training dashboard: throughput, loss, checkpointing, mesh health."""
    rows = [
        ("Train tokens/s", ["ray_tpu_train_tokens_per_sec"], "short"),
        ("Train loss", ["ray_tpu_train_loss"], "short"),
        ("Step time", ["ray_tpu_train_step_seconds"], "s"),
        ("MFU", ["ray_tpu_train_mfu"], "percentunit"),
        ("Checkpoint save seconds", ["ray_tpu_checkpoint_save_seconds"],
         "s"),
        ("Trials running", ["ray_tpu_tune_trials_running"], "short"),
    ]
    panels = []
    for i, (title, exprs, unit) in enumerate(rows):
        panels.append(_panel(i + 1, title, exprs, unit=unit,
                             x=(i % 2) * 12, y=(i // 2) * 8))
    return _dashboard("ray-tpu-train", "ray_tpu train", panels)


def generate_serve_dashboard() -> Dict[str, Any]:
    """Serving dashboard: QPS, latency, queue depth, replicas."""
    rows = [
        ("Requests/s", ["rate(ray_tpu_serve_requests_total[1m])"], "reqps"),
        ("Errors/s", ["rate(ray_tpu_serve_errors_total[1m])"], "reqps"),
        ("Latency p50/p99", [
            "histogram_quantile(0.5, rate(ray_tpu_serve_latency_seconds_bucket[1m]))",
            "histogram_quantile(0.99, rate(ray_tpu_serve_latency_seconds_bucket[1m]))",
        ], "s"),
        ("Replica queue depth", ["ray_tpu_serve_queue_depth"], "short"),
        ("Replicas per deployment", ["ray_tpu_serve_replicas"], "short"),
    ]
    panels = []
    for i, (title, exprs, unit) in enumerate(rows):
        panels.append(_panel(i + 1, title, exprs, unit=unit,
                             x=(i % 2) * 12, y=(i // 2) * 8))
    return _dashboard("ray-tpu-serve", "ray_tpu serve", panels)


_FACTORIES = {
    "core": generate_default_dashboard,
    "train": generate_train_dashboard,
    "serve": generate_serve_dashboard,
}


def export_dashboards(out_dir: str,
                      which: Optional[List[str]] = None) -> List[str]:
    """Write dashboard JSON files for Grafana provisioning; returns paths."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name in which or sorted(_FACTORIES):
        path = os.path.join(out_dir, f"ray_tpu_{name}.json")
        with open(path, "w") as f:
            json.dump(_FACTORIES[name](), f, indent=2)
        paths.append(path)
    return paths
