from ray_tpu.parallel.mesh import (
    MeshConfig,
    make_hybrid_mesh,
    make_mesh,
    make_virtual_mesh,
    AxisRules,
    DEFAULT_RULES,
    logical_sharding,
    shard_pytree,
)
from ray_tpu.parallel.distributed import (
    initialize_from_session,
    initialize_group,
    shutdown_group,
)
