"""Multi-host JAX initialization inside dynamically-created actors.

SURVEY hard-part #4: `jax.distributed.initialize` expects a static world at
process start, but this framework creates worker groups dynamically (Train
spawns one actor per host). This module bridges the two through the control
plane's KV store — the same place the reference rendezvouses NCCL unique
ids (`collective_group/nccl_collective_group.py`): rank 0 binds a free
coordinator port and publishes `host:port` under the group's KV key; every
rank polls the key and calls `jax.distributed.initialize(addr, world,
rank)`. After it returns, `jax.devices()` spans all processes, and a
`make_mesh` over them compiles collectives across hosts (ICI within a
slice, DCN across — or Gloo on CPU test rigs).
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Optional

logger = logging.getLogger(__name__)

_KV_NS = "_jax_distributed"
_initialized_group: Optional[str] = None


def _kv():
    from ray_tpu.core.api import _global_worker

    return _global_worker().gcs


def _my_host() -> str:
    from ray_tpu.core.api import _global_worker

    addr = _global_worker().address  # "host:port" of this worker's server
    return addr.rsplit(":", 1)[0] if ":" in addr else "127.0.0.1"


def initialize_group(rank: int, world_size: int, *,
                     group_name: str = "default",
                     timeout: float = 120.0) -> None:
    """Join this process into a jax.distributed world of `world_size`
    processes. Call before any other JAX backend use in the process.
    Idempotent per group; re-initializing a different group raises.
    """
    global _initialized_group
    import os

    import jax

    # Respect JAX_PLATFORMS even when a sitecustomize pinned the platform
    # via jax.config (config beats the env var; worker pools export
    # JAX_PLATFORMS=cpu for CPU worker fleets).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    if _initialized_group is not None:
        if _initialized_group == group_name:
            return
        raise RuntimeError(
            f"process already in jax.distributed group {_initialized_group!r}")
    if world_size == 1:
        _initialized_group = group_name
        return

    key = f"coordinator:{group_name}".encode()
    gcs = _kv()
    if rank == 0:
        # Hold the bound socket (SO_REUSEADDR) until just before initialize
        # to shrink the pick-port/bind race to microseconds.
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((_my_host(), 0))
        coord = f"{s.getsockname()[0]}:{s.getsockname()[1]}"
        gcs.call("kv_put", {"namespace": _KV_NS, "key": key,
                            "value": coord.encode()})
        s.close()
    else:
        # A stale key from a previous run of this group may still be in the
        # KV; only accept a coordinator that is actually listening (the old
        # process is dead -> refused -> keep polling until the new rank 0
        # overwrites the key and binds).
        deadline = time.monotonic() + timeout
        coord = None
        while time.monotonic() < deadline:
            v = gcs.call("kv_get", {"namespace": _KV_NS, "key": key})
            if v:
                host, port = v.decode().rsplit(":", 1)
                try:
                    socket.create_connection((host, int(port)),
                                             timeout=1).close()
                    coord = v.decode()
                    break
                except OSError:
                    pass
            time.sleep(0.1)
        if coord is None:
            raise TimeoutError(
                f"rank {rank}: no live coordinator for group "
                f"{group_name!r} within {timeout}s")

    logger.info("rank %d/%d joining jax.distributed at %s", rank, world_size,
                coord)
    jax.distributed.initialize(coord, num_processes=world_size,
                               process_id=rank)
    _initialized_group = group_name


def initialize_from_session(group_name: str = "default",
                            timeout: float = 120.0) -> None:
    """Inside a Train worker: rank/world come from the AIR session."""
    from ray_tpu.air import session

    initialize_group(session.get_world_rank(), session.get_world_size(),
                     group_name=group_name, timeout=timeout)


def shutdown_group(group_name: str = "default") -> None:
    global _initialized_group
    import jax

    if _initialized_group is None:
        return
    try:
        jax.distributed.shutdown()
    except (RuntimeError, ValueError):
        pass  # never initialized / already shut down
    try:
        _kv().call("kv_del", {"namespace": _KV_NS,
                              "key": f"coordinator:{group_name}".encode()})
    except (OSError, RuntimeError, TimeoutError):
        pass  # GCS already down at interpreter exit
    _initialized_group = None
