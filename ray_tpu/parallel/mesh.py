"""Device meshes and logical-axis shardings: the NCCL replacement.

Where the reference wires NCCL process groups through actors
(`python/ray/util/collective/collective.py:120`) and torch DDP/FSDP
(`python/ray/train/torch/config.py:69`), the TPU-native design gives every
worker group a `jax.sharding.Mesh` whose axes map onto the hardware:

    dp    — data parallel, outermost (across slices -> rides DCN)
    fsdp  — sharded data parallel (ZeRO-3 analog; within slice -> ICI)
    tp    — tensor parallel (within slice -> ICI, highest bandwidth)
    sp    — sequence/context parallel (ring collectives over ICI)
    ep    — expert parallel for MoE layers (reuses fsdp axis by default)

Collectives (`psum`, `all_gather`, `ppermute`, `reduce_scatter`) are then
emitted by XLA from sharding annotations — no collective library calls in
user code. Parameters/activations carry *logical* axis names which
`AxisRules` maps to mesh axes (the flax `logical_axis_rules` idea, re-built
standalone).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of each parallelism axis. -1 on `dp` means 'fill'.

    `pp` (pipeline parallel) is manual-mode: the pp axis is only used by
    `ray_tpu.parallel.pipeline` (shard_map over 'pp'); the auto-sharded
    train step requires pp == 1.
    """

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.pp * self.fsdp * self.tp * self.sp
        dp = self.dp
        if dp == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by pp*fsdp*tp*sp={fixed}")
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.pp}x{self.fsdp}x{self.tp}x{self.sp} "
                f"!= {n_devices} devices")
        return MeshConfig(dp=dp, fsdp=self.fsdp, tp=self.tp, sp=self.sp,
                          pp=self.pp)

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dp, self.pp, self.fsdp, self.tp, self.sp)


# pp sits between dp and fsdp: stage boundaries cross lower-bandwidth links
# than tp/sp (which stay innermost on ICI neighbors).
AXIS_NAMES = ("dp", "pp", "fsdp", "tp", "sp")


def make_mesh(config: MeshConfig, devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Build a Mesh with (dp, pp, fsdp, tp, sp) axes over the given devices.

    Axis order is chosen so the innermost (fastest-varying) axes hold the
    highest-bandwidth collectives: tp/sp innermost map to adjacent chips on
    ICI; dp outermost maps across hosts/slices (DCN for multi-slice).
    """
    devices = list(devices if devices is not None else jax.devices())
    cfg = config.resolve(len(devices))
    arr = np.array(devices).reshape(cfg.shape)
    return Mesh(arr, AXIS_NAMES)


def make_hybrid_mesh(config: MeshConfig, *, dcn_dp: int = 1,
                     devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Multi-slice mesh: `dcn_dp` data-parallel replicas across slices (DCN),
    `config` parallelism within each slice (ICI).

    Uses `mesh_utils.create_hybrid_device_mesh` so device order guarantees
    only the outermost dp axis crosses slice boundaries — tp/sp/fsdp
    collectives stay on ICI (the scaling-book multislice recipe). Falls back
    to a plain reshape when devices carry no slice topology (CPU tests,
    single slice): semantics identical, placement guarantee vacuous.
    """
    devices = list(devices if devices is not None else jax.devices())
    if dcn_dp <= 1:
        return make_mesh(config, devices)
    if len(devices) % dcn_dp != 0:
        raise ValueError(f"{len(devices)} devices not divisible by dcn_dp={dcn_dp}")
    per_slice = config.resolve(len(devices) // dcn_dp)
    if getattr(devices[0], "slice_index", None) is not None:
        from jax.experimental import mesh_utils

        # real multislice topology: let genuine shape mismatches propagate
        arr = mesh_utils.create_hybrid_device_mesh(
            per_slice.shape, (dcn_dp, 1, 1, 1, 1), devices=devices)
    else:  # no slice topology (CPU tests, single slice): plain reshape
        arr = np.array(devices).reshape(
            (dcn_dp * per_slice.dp,) + per_slice.shape[1:])
    return Mesh(arr, AXIS_NAMES)


def make_virtual_mesh(n_devices: int, config: Optional[MeshConfig] = None) -> Mesh:
    """CPU-device mesh for tests/dryrun (xla_force_host_platform_device_count)."""
    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}")
    cfg = (config or MeshConfig()).resolve(n_devices)
    return make_mesh(cfg, devices[:n_devices])


# --------------------------------------------------------------------------
# Logical axis rules


class AxisRules:
    """Maps logical axis names -> mesh axis (or None = replicated)."""

    def __init__(self, rules: Dict[str, Any]):
        self.rules = dict(rules)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        parts = []
        used = set()
        for ax in logical_axes:
            m = self.rules.get(ax) if ax is not None else None
            if m is None:
                parts.append(None)
            else:
                # a logical axis may map to a tuple of mesh axes
                key = tuple(m) if isinstance(m, (tuple, list)) else (m,)
                free = tuple(a for a in key if a not in used)
                used.update(free)
                parts.append(free if len(free) != 1 else free[0])
                if not free:
                    parts[-1] = None
        return P(*parts)

    def sharding(self, mesh: Mesh, logical_axes: Sequence[Optional[str]]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes))


# Default rules for transformer LMs: FSDP shards the embed dim of weights,
# TP shards heads/mlp, batch shards over (dp, fsdp) [fsdp acts as extra DP
# for activations, ZeRO-style], sequence shards over sp.
DEFAULT_RULES = AxisRules({
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "expert": "fsdp",
})


def logical_sharding(mesh: Mesh, axes_tree: Any, rules: AxisRules = DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree_util.tree_map(
        lambda axes: rules.sharding(mesh, axes),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_pytree(tree: Any, shardings: Any):
    """Device-put a pytree with the given shardings (host -> sharded device)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings)
