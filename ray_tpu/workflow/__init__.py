from ray_tpu.workflow.api import (
    WorkflowCancelledError, cancel, delete, get_output, get_status,
    list_all, resume, run, run_async, send_event, step, wait_for_event)
