from ray_tpu.workflow.api import step, run, run_async, resume, list_all, get_status
