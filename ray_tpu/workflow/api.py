"""Workflows: durable DAG execution with storage-backed step checkpoints.

Mirrors the reference workflow library's capability
(`python/ray/workflow/workflow_executor.py`, `workflow_storage.py`,
`event_listener.py`): every step's result is persisted under the
workflow's storage directory before dependents run, so a crashed/cancelled
workflow `resume()`s from the last completed step instead of recomputing.
INDEPENDENT steps execute concurrently (one in-flight task per ready DAG
node, like the reference executor's dag-level parallelism), steps can
block on DURABLE EVENTS (`wait_for_event` / `send_event` — delivery is
persisted, so an event received before a crash survives the resume), and
workflows are manageable: `cancel`, `get_output`, `delete`, `get_status`,
`list_all`.

    @workflow.step
    def add(a, b): return a + b

    out = workflow.run(add.step(add.step(1, 2), 3), workflow_id="w1",
                       storage="/tmp/wf")
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.core.exceptions import TaskCancelledError

_DEFAULT_STORAGE = os.path.join(
    os.environ.get("RAY_TPU_WORKFLOW_STORAGE",
                   os.path.expanduser("~/.ray_tpu/workflows")))


class WorkflowStep:
    """A lazy step invocation (node in the workflow DAG)."""

    def __init__(self, fn, args, kwargs, name: Optional[str] = None,
                 max_retries: int = 0):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or fn.__name__
        self.max_retries = max_retries

    def step_id(self) -> str:
        """Deterministic id from the step's position in the DAG."""
        h = hashlib.blake2b(digest_size=8)
        h.update(self.name.encode())
        for a in self.args:
            h.update(a.step_id().encode() if isinstance(a, WorkflowStep)
                     else repr(a).encode())
        for k, v in sorted(self.kwargs.items()):
            h.update(k.encode())
            h.update(v.step_id().encode() if isinstance(v, WorkflowStep)
                     else repr(v).encode())
        return f"{self.name}-{h.hexdigest()}"


class WorkflowCancelledError(TaskCancelledError, RuntimeError):
    """The workflow was cancelled (workflow.cancel) mid-execution.

    A subclass of the runtime's typed TaskCancelledError: callers that
    match cancellation BY TYPE (the job storm, generic task supervisors)
    catch workflow cancellation the same way; RuntimeError is kept as a
    base for pre-existing handlers."""


class EventStep(WorkflowStep):
    """A DAG node that becomes ready when a named DURABLE event arrives
    (reference workflow events, `python/ray/workflow/event_listener.py`):
    `send_event` persists the payload under the workflow's storage, so an
    event delivered before a crash is still there after resume()."""

    def __init__(self, event_name: str):
        super().__init__(fn=None, args=(), kwargs={},
                         name=f"event::{event_name}")
        self.event_name = event_name

    def step_id(self) -> str:
        return f"event-{self.event_name}"


def wait_for_event(event_name: str) -> EventStep:
    """A step whose value is the event's payload; dependents run only
    after `send_event(workflow_id, event_name, ...)`."""
    return EventStep(event_name)


def send_event(workflow_id: str, event_name: str, payload=None, *,
               storage: Optional[str] = None, create: bool = False) -> None:
    """Deliver (and persist) an event. The workflow must EXIST — a typo'd
    id errors instead of silently minting a ghost directory — unless
    create=True, the explicit pre-delivery form for events that arrive
    before the workflow starts (delivery is durable either way)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=create)
    if not st.exists():
        raise ValueError(f"no workflow {workflow_id!r} under storage "
                         "(send_event(..., create=True) pre-delivers)")
    st.save_event(event_name, payload)


class _StepBuilder:
    def __init__(self, fn, **opts):
        self.fn = fn
        self.opts = opts

    def step(self, *args, **kwargs) -> WorkflowStep:
        return WorkflowStep(self.fn, args, kwargs, **self.opts)

    def options(self, **opts) -> "_StepBuilder":
        merged = dict(self.opts)
        merged.update(opts)
        return _StepBuilder(self.fn, **merged)


def step(fn=None, *, name: Optional[str] = None, max_retries: int = 0):
    """Decorator: `@workflow.step` (reference workflow step API)."""
    if fn is not None:
        return _StepBuilder(fn)

    def deco(f):
        return _StepBuilder(f, name=name, max_retries=max_retries)

    return deco


# ------------------------------------------------------------------ storage


class _Storage:
    def __init__(self, root: str, workflow_id: str, create: bool = False):
        """create=False (read/manage paths) must not resurrect deleted
        workflows or mint ghost dirs for typo'd ids."""
        self.dir = os.path.join(root, workflow_id)
        if create:
            os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def exists(self) -> bool:
        return os.path.isdir(self.dir)

    def _path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", step_id + ".pkl")

    def has(self, step_id: str) -> bool:
        return os.path.exists(self._path(step_id))

    def load(self, step_id: str):
        with open(self._path(step_id), "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value) -> None:
        tmp = self._path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(step_id))

    def set_meta(self, **kv) -> None:
        path = os.path.join(self.dir, "meta.json")
        meta = {}
        if os.path.exists(path):
            with open(path) as f:
                meta = json.load(f)
        meta.update(kv)
        with open(path, "w") as f:
            json.dump(meta, f)

    def get_meta(self) -> dict:
        path = os.path.join(self.dir, "meta.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    def save_dag(self, root_step: WorkflowStep) -> None:
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(root_step, f)

    def load_dag(self) -> WorkflowStep:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def _event_path(self, name: str) -> str:
        return os.path.join(self.dir, "events", name + ".pkl")

    def save_event(self, name: str, payload) -> None:
        os.makedirs(os.path.join(self.dir, "events"), exist_ok=True)
        tmp = self._event_path(name) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(payload, f)
        os.replace(tmp, self._event_path(name))

    def has_event(self, name: str) -> bool:
        return os.path.exists(self._event_path(name))

    def load_event(self, name: str):
        with open(self._event_path(name), "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------- executor


@ray_tpu.remote
def _run_step(fn_blob: bytes, args, kwargs):
    fn = cloudpickle.loads(fn_blob)
    return fn(*args, **kwargs)


def _execute(root: WorkflowStep, storage: _Storage):
    """Topological executor: every READY node (deps persisted) runs as its
    own in-flight task, so independent DAG branches execute CONCURRENTLY
    (reference workflow_executor dag parallelism); results persist before
    dependents become ready. Event steps become ready when their event
    file exists; cancel() flips the persisted status and the loop raises.
    """
    nodes: Dict[str, WorkflowStep] = {}
    deps: Dict[str, List[str]] = {}

    def visit(n: WorkflowStep) -> str:
        sid = n.step_id()
        if sid in nodes:
            return sid
        nodes[sid] = n
        child_ids = [visit(a) for a in n.args if isinstance(a, WorkflowStep)]
        child_ids += [visit(v) for v in n.kwargs.values()
                      if isinstance(v, WorkflowStep)]
        deps[sid] = child_ids
        return sid

    root_id = visit(root)
    results: Dict[str, Any] = {
        sid: storage.load(sid) for sid in nodes if storage.has(sid)}
    attempts_left = {sid: nodes[sid].max_retries for sid in nodes}
    inflight: Dict[Any, str] = {}  # result ref -> step id
    last_exc: Optional[Exception] = None

    def resolved(v):
        return results[v.step_id()] if isinstance(v, WorkflowStep) else v

    while root_id not in results:
        if storage.get_meta().get("status") == "CANCELED":
            # drain ALREADY-FINISHED in-flight steps so their results
            # persist for a later resume, then CANCEL the rest through the
            # runtime's real cancel (their refs resolve to the typed
            # TaskCancelledError instead of running to completion)
            if inflight:
                done, running = ray_tpu.wait(list(inflight),
                                             num_returns=len(inflight),
                                             timeout=5.0)
                for ref in done:
                    sid = inflight.pop(ref)
                    try:
                        value = ray_tpu.get(ref)
                    except Exception:
                        continue
                    storage.save(sid, value)
                    results[sid] = value
                for ref in running:
                    try:
                        ray_tpu.cancel(ref)
                    except Exception:
                        pass  # best-effort: the step re-runs on resume()
            raise WorkflowCancelledError(
                f"workflow cancelled with {len(results)}/{len(nodes)} "
                f"steps complete")
        launched = False
        for sid, n in nodes.items():
            if (sid in results or sid in inflight.values()
                    or any(d not in results for d in deps[sid])):
                continue
            if isinstance(n, EventStep):
                if storage.has_event(n.event_name):
                    value = storage.load_event(n.event_name)
                    storage.save(sid, value)
                    results[sid] = value
                    launched = True
                continue  # not delivered yet: poll next loop
            args = [resolved(a) for a in n.args]
            kwargs = {k: resolved(v) for k, v in n.kwargs.items()}
            ref = _run_step.remote(cloudpickle.dumps(n.fn), args, kwargs)
            inflight[ref] = sid
            launched = True
        if root_id in results:
            break
        if not inflight:
            if launched:
                continue
            if any(isinstance(nodes[s], EventStep) for s in nodes
                   if s not in results):
                time.sleep(0.2)  # waiting purely on external events
                continue
            raise last_exc or RuntimeError("workflow made no progress")
        done, _ = ray_tpu.wait(list(inflight), num_returns=1, timeout=1.0)
        for ref in done:
            sid = inflight.pop(ref)
            try:
                value = ray_tpu.get(ref)
            except Exception as e:
                if attempts_left[sid] > 0:
                    attempts_left[sid] -= 1
                    last_exc = e
                    continue  # becomes ready again next loop
                raise
            storage.save(sid, value)
            results[sid] = value
    return results[root_id]


def _run_to_completion(st: _Storage, root: WorkflowStep):
    """Shared status-transition policy for run()/resume()."""
    st.set_meta(status="RUNNING")
    try:
        out = _execute(root, st)
        st.set_meta(status="SUCCEEDED", end_time=time.time())
        return out
    except WorkflowCancelledError as e:
        # status already CANCELED (don't overwrite with FAILED) — but
        # RECORD the typed error so get_status/list_all surface why
        st.set_meta(error=str(e), end_time=time.time())
        raise
    except Exception as e:
        st.set_meta(status="FAILED", error=str(e), end_time=time.time())
        raise


def run(root: WorkflowStep, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None):
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=True)
    if st.get_meta().get("status") == "CANCELED":
        # a cancel that landed before the (async) driver started must
        # stick; resume() is the explicit un-cancel path
        raise WorkflowCancelledError(
            f"workflow {workflow_id!r} was cancelled before it started")
    st.save_dag(root)
    st.set_meta(start_time=time.time())
    return _run_to_completion(st, root)


def run_async(root: WorkflowStep, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None):
    """Run in a background task; returns an ObjectRef of the result."""

    @ray_tpu.remote
    def driver(blob, wf_id, storage_root):
        from ray_tpu.workflow import api as wf_api

        node = cloudpickle.loads(blob)
        return wf_api.run(node, workflow_id=wf_id, storage=storage_root)

    return driver.remote(cloudpickle.dumps(root),
                         workflow_id or f"wf-{int(time.time() * 1000)}",
                         storage or _DEFAULT_STORAGE)


def resume(workflow_id: str, *, storage: Optional[str] = None):
    """Resume from persisted step results (completed steps are not re-run)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    if not st.exists():
        raise ValueError(f"no workflow {workflow_id!r} under storage")
    root = st.load_dag()
    return _run_to_completion(st, root)


def get_status(workflow_id: str, *, storage: Optional[str] = None) -> Optional[str]:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    return st.get_meta().get("status")


def cancel(workflow_id: str, *, storage: Optional[str] = None) -> None:
    """Cancel a running workflow (reference workflow.cancel): the executor
    observes the persisted status flip, drains finished in-flight steps
    (their results persist for a later resume()), and stops launching new
    ones; steps already running on workers run to completion."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    if not st.exists():
        raise ValueError(f"no workflow {workflow_id!r} under storage")
    st.set_meta(status="CANCELED", end_time=time.time())


def get_output(workflow_id: str, *, storage: Optional[str] = None):
    """Result of a SUCCEEDED workflow from storage (reference
    workflow.get_output), without re-running anything."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    meta = st.get_meta()
    if meta.get("status") != "SUCCEEDED":
        raise ValueError(
            f"workflow {workflow_id!r} is {meta.get('status')!r}, "
            "not SUCCEEDED; resume() it first")
    root = st.load_dag()
    return st.load(root.step_id())


def delete(workflow_id: str, *, storage: Optional[str] = None) -> None:
    """Remove a workflow's storage (reference workflow.delete)."""
    import shutil

    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    shutil.rmtree(st.dir, ignore_errors=True)


def list_all(storage: Optional[str] = None) -> List[Dict[str, Any]]:
    root = storage or _DEFAULT_STORAGE
    out = []
    if not os.path.isdir(root):
        return out
    for wf_id in sorted(os.listdir(root)):
        st = _Storage(root, wf_id)
        meta = st.get_meta()
        out.append({"workflow_id": wf_id, **meta})
    return out
