"""Workflows: durable DAG execution with storage-backed step checkpoints.

Mirrors the reference workflow library's capability
(`python/ray/workflow/workflow_executor.py`, `workflow_storage.py`): every
step's result is persisted under the workflow's storage directory before
dependents run, so a crashed/cancelled workflow `resume()`s from the last
completed step instead of recomputing.

    @workflow.step
    def add(a, b): return a + b

    out = workflow.run(add.step(add.step(1, 2), 3), workflow_id="w1",
                       storage="/tmp/wf")
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu

_DEFAULT_STORAGE = os.path.join(
    os.environ.get("RAY_TPU_WORKFLOW_STORAGE",
                   os.path.expanduser("~/.ray_tpu/workflows")))


class WorkflowStep:
    """A lazy step invocation (node in the workflow DAG)."""

    def __init__(self, fn, args, kwargs, name: Optional[str] = None,
                 max_retries: int = 0):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or fn.__name__
        self.max_retries = max_retries

    def step_id(self) -> str:
        """Deterministic id from the step's position in the DAG."""
        h = hashlib.blake2b(digest_size=8)
        h.update(self.name.encode())
        for a in self.args:
            h.update(a.step_id().encode() if isinstance(a, WorkflowStep)
                     else repr(a).encode())
        for k, v in sorted(self.kwargs.items()):
            h.update(k.encode())
            h.update(v.step_id().encode() if isinstance(v, WorkflowStep)
                     else repr(v).encode())
        return f"{self.name}-{h.hexdigest()}"


class _StepBuilder:
    def __init__(self, fn, **opts):
        self.fn = fn
        self.opts = opts

    def step(self, *args, **kwargs) -> WorkflowStep:
        return WorkflowStep(self.fn, args, kwargs, **self.opts)

    def options(self, **opts) -> "_StepBuilder":
        merged = dict(self.opts)
        merged.update(opts)
        return _StepBuilder(self.fn, **merged)


def step(fn=None, *, name: Optional[str] = None, max_retries: int = 0):
    """Decorator: `@workflow.step` (reference workflow step API)."""
    if fn is not None:
        return _StepBuilder(fn)

    def deco(f):
        return _StepBuilder(f, name=name, max_retries=max_retries)

    return deco


# ------------------------------------------------------------------ storage


class _Storage:
    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(root, workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def _path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", step_id + ".pkl")

    def has(self, step_id: str) -> bool:
        return os.path.exists(self._path(step_id))

    def load(self, step_id: str):
        with open(self._path(step_id), "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value) -> None:
        tmp = self._path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(step_id))

    def set_meta(self, **kv) -> None:
        path = os.path.join(self.dir, "meta.json")
        meta = {}
        if os.path.exists(path):
            with open(path) as f:
                meta = json.load(f)
        meta.update(kv)
        with open(path, "w") as f:
            json.dump(meta, f)

    def get_meta(self) -> dict:
        path = os.path.join(self.dir, "meta.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    def save_dag(self, root_step: WorkflowStep) -> None:
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(root_step, f)

    def load_dag(self) -> WorkflowStep:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------- executor


@ray_tpu.remote
def _run_step(fn_blob: bytes, args, kwargs):
    fn = cloudpickle.loads(fn_blob)
    return fn(*args, **kwargs)


def _execute(node: WorkflowStep, storage: _Storage):
    step_id = node.step_id()
    if storage.has(step_id):
        return storage.load(step_id)
    args = [_execute(a, storage) if isinstance(a, WorkflowStep) else a
            for a in node.args]
    kwargs = {k: (_execute(v, storage) if isinstance(v, WorkflowStep) else v)
              for k, v in node.kwargs.items()}
    attempts = node.max_retries + 1
    last_exc: Optional[Exception] = None
    for _ in range(attempts):
        try:
            value = ray_tpu.get(_run_step.remote(
                cloudpickle.dumps(node.fn), args, kwargs))
            storage.save(step_id, value)
            return value
        except Exception as e:
            last_exc = e
    raise last_exc  # type: ignore[misc]


def run(root: WorkflowStep, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None):
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    st.save_dag(root)
    st.set_meta(status="RUNNING", start_time=time.time())
    try:
        out = _execute(root, st)
        st.set_meta(status="SUCCEEDED", end_time=time.time())
        return out
    except Exception as e:
        st.set_meta(status="FAILED", error=str(e), end_time=time.time())
        raise


def run_async(root: WorkflowStep, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None):
    """Run in a background task; returns an ObjectRef of the result."""

    @ray_tpu.remote
    def driver(blob, wf_id, storage_root):
        from ray_tpu.workflow import api as wf_api

        node = cloudpickle.loads(blob)
        return wf_api.run(node, workflow_id=wf_id, storage=storage_root)

    return driver.remote(cloudpickle.dumps(root),
                         workflow_id or f"wf-{int(time.time() * 1000)}",
                         storage or _DEFAULT_STORAGE)


def resume(workflow_id: str, *, storage: Optional[str] = None):
    """Resume from persisted step results (completed steps are not re-run)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    root = st.load_dag()
    st.set_meta(status="RUNNING")
    try:
        out = _execute(root, st)
        st.set_meta(status="SUCCEEDED", end_time=time.time())
        return out
    except Exception as e:
        st.set_meta(status="FAILED", error=str(e), end_time=time.time())
        raise


def get_status(workflow_id: str, *, storage: Optional[str] = None) -> Optional[str]:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    return st.get_meta().get("status")


def list_all(storage: Optional[str] = None) -> List[Dict[str, Any]]:
    root = storage or _DEFAULT_STORAGE
    out = []
    if not os.path.isdir(root):
        return out
    for wf_id in sorted(os.listdir(root)):
        st = _Storage(root, wf_id)
        meta = st.get_meta()
        out.append({"workflow_id": wf_id, **meta})
    return out
