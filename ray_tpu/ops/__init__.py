from ray_tpu.ops.layers import rms_norm, rotary_embedding, apply_rotary, swiglu
from ray_tpu.ops.attention import attention, causal_attention_reference
from ray_tpu.ops.ring_attention import ring_attention
