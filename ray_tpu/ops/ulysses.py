"""Ulysses sequence parallelism: all-to-all head/sequence re-sharding.

Green-field relative to the reference (SURVEY §5.7: no sequence/context
parallelism exists anywhere in it). Complements ring attention
(`ops/ring_attention.py`) as the second standard SP scheme (DeepSpeed-
Ulysses, Jacobs et al.): activations arrive sequence-sharded over the `sp`
mesh axis; an all-to-all re-shards them to *head*-sharded with the full
sequence local, plain (flash) attention runs per device, and a second
all-to-all restores sequence sharding.

Trade-off vs ring: Ulysses moves activations twice over ICI
(2 x O(b*s*d/sp) per device, as all-to-alls XLA can't overlap with the
attention itself) but runs one dense attention kernel with no per-step
masking overhead; ring keeps transfers to K/V only and overlaps them with
compute, but pays the online-softmax merge per ring step. Ulysses requires
sp | local head count; ring has no head constraint. Both are exposed via
`ModelConfig.seq_parallel` and compared against dense attention in tests.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import attention
from ray_tpu.util.jax_compat import shard_map


def _repeat_kv_to_multiple(t: jax.Array, sp: int) -> jax.Array:
    """Repeat KV heads (adjacently, GQA grouping order) by the minimal
    factor that makes the head count divisible by sp."""
    h = t.shape[1]
    if h % sp == 0:
        return t
    rep = sp // math.gcd(h, sp)
    b, _, s, d = t.shape
    return jnp.broadcast_to(t[:, :, None], (b, h, rep, s, d)).reshape(
        b, h * rep, s, d)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "sp", causal: bool = True,
                      sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard Ulysses attention; call inside shard_map over `axis_name`.

    Shapes are local shards [batch, heads, seq/sp, head_dim]. GQA is
    supported natively: KV heads cross the all-to-all unexpanded (repeated
    only to the minimal sp-divisible multiple), and `attention()` broadcasts
    them to the Q head count after the re-shard — so KV ICI traffic stays
    ~n_kv/n_heads of the naive pre-repeat. Q's local head count must be
    divisible by the sp axis size.
    """
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % sp != 0:
        raise ValueError(f"local Q head count {h} not divisible by sp={sp}")
    k = _repeat_kv_to_multiple(k, sp)
    v = _repeat_kv_to_multiple(v, sp)

    def scatter_heads(t):  # [b, h, s/sp, d] -> [b, h/sp, s, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def gather_heads(t):   # [b, h/sp, s, d] -> [b, h, s/sp, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return gather_heads(out)


def ulysses_attention_sharded(mesh: Mesh, q, k, v, *, causal: bool = True,
                              axis_name: str = "sp",
                              sm_scale: Optional[float] = None):
    """shard_map wrapper: [batch, heads, seq, head_dim] global arrays with
    seq sharded over `axis_name`; batch over (dp, fsdp); heads over tp."""
    spec = P(("dp", "fsdp"), "tp", axis_name, None)
    fn = functools.partial(
        ulysses_attention, axis_name=axis_name, causal=causal,
        sm_scale=sm_scale)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
