"""Elementwise/normalization building blocks, XLA-fusion-friendly.

These are deliberately thin: on TPU the win is letting XLA fuse them into
surrounding matmuls, not hand-scheduling. The pallas fused RMSNorm
(`ray_tpu.ops.pallas.rmsnorm`) exists for the cases XLA's fusion misses
(very long rows at small batch); `rms_norm` dispatches there when profitable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm: x * w / sqrt(mean(x^2)). Computed in fp32, cast back."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def rotary_embedding(positions: jax.Array, head_dim: int,
                     theta: float = 500000.0) -> tuple[jax.Array, jax.Array]:
    """RoPE cos/sin tables for given positions. Llama-3 default theta."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., seq, hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply RoPE to [..., seq, heads, head_dim] given [..., seq, hd/2] tables."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    # broadcast tables over the heads axis
    cos = cos[..., :, None, :]
    sin = sin[..., :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(x_gate: jax.Array, x_up: jax.Array) -> jax.Array:
    """SwiGLU activation: silu(gate) * up."""
    return jax.nn.silu(x_gate) * x_up
